"""Long-context sequence/context parallelism: ring attention + all-to-all.

The reference is a pre-LLM data-parallel library with no sequence dimension
(SURVEY.md §5 "long-context": absent), but its core primitive — neighbor
exchange along a ring with compute overlapped — is exactly the communication
pattern of ring attention.  This module makes long context a first-class
capability of the framework by reusing the gossip machinery's ppermute ring:

- :func:`ring_attention` — blockwise attention with the KV blocks rotating
  around the mesh axis (one ``lax.ppermute`` per step, riding the ICI ring),
  combined with a numerically stable online softmax (flash-attention-style
  running max / denominator).  Memory per device is O(T/n), enabling
  sequences n× longer than single-device attention.
- :func:`all_to_all_attention` — DeepSpeed-Ulysses-style sequence parallelism:
  ``lax.all_to_all`` resharding sequence↔heads, full local attention, and the
  inverse reshard.  Fewer collective steps than the ring (2 all-to-alls vs
  n-1 permutes) but requires ``num_heads % axis_size == 0``.

Both run inside ``shard_map`` with the sequence dimension sharded over
``axis_name``; both are jit/grad compatible (the backward pass re-runs the
rotation in reverse via XLA's transpose of ``ppermute``).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from bluefog_tpu.ops.collectives import axis_size as _axis_size

__all__ = [
    "ring_attention",
    "all_to_all_attention",
    "local_attention",
    "zigzag_shard",
    "zigzag_unshard",
]

_NEG_INF = -1e30  # large finite negative: avoids -inf NaN traps in exp


def _flash_eligible(q, k, causal, q_offset, k_offset) -> bool:
    """Static eligibility check for the fused TPU flash kernel.

    The Pallas kernel (``jax.experimental.pallas.ops.tpu.flash_attention``)
    needs: a TPU backend, sequence length a multiple of its 128-row block,
    equal q/k lengths, and — because its causal mask is the standard aligned
    one — *static* offsets with ``q_offset == k_offset`` when causal.
    """
    if jax.default_backend() not in ("tpu", "axon"):
        return False
    if not (isinstance(q_offset, int) and isinstance(k_offset, int)):
        return False
    if causal and q_offset != k_offset:
        return False
    t_q, t_k = q.shape[1], k.shape[1]
    return t_q == t_k and t_q >= 128 and t_q % 128 == 0 and q.shape[-1] >= 32


def _flash_block_sizes(t: int, block: Optional[int] = None):
    """Tile sizes for the fused TPU kernel.

    The library default is 128 everywhere (its own source marks parameter
    selection as a TODO), which leaves the MXU under-fed: on a v5e at
    T=4096 the default-tiled kernel measured *slower* than the dense path
    despite doing half the causal FLOPs.  Larger tiles amortize the grid
    loop; ``block`` overrides the target edge (the benchmark's --tune mode
    sweeps it), otherwise 512 — the largest tile that still fits the
    backward pass's working set in v5e VMEM comfortably.  Every edge is
    clamped to the largest power-of-two divisor of ``t`` (the kernel
    requires exact tiling; T is a multiple of 128 per `_flash_eligible`).
    """
    from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes

    target = block or 512
    edge = 128
    while edge * 2 <= target and t % (edge * 2) == 0:
        edge *= 2
    return BlockSizes(
        block_q=edge, block_k_major=edge, block_k=edge, block_b=1,
        block_q_major_dkv=edge, block_k_major_dkv=edge, block_k_dkv=edge,
        block_q_dkv=edge, block_k_major_dq=edge, block_k_dq=edge,
        block_q_dq=edge)


def local_attention(q, k, v, *, causal: bool = False, scale: Optional[float] = None,
                    q_offset=0, k_offset=0, backend: str = "dense",
                    flash_block: Optional[int] = None):
    """Plain softmax attention on local blocks (also the Ulysses inner step).

    Shapes: ``q (B, Tq, H, D)``, ``k/v (B, Tk, H, D)`` → ``(B, Tq, H, D)``.
    ``q_offset``/``k_offset`` are the *global* positions of the first query /
    key row, used for causal masking of shifted blocks (may be traced).

    ``backend``: ``'dense'`` (default) materializes the (Tq, Tk) scores
    (portable, covered by CI); ``'flash'`` forces the fused Pallas TPU kernel
    (O(T) memory, fwd+bwd); ``'auto'`` picks flash whenever
    :func:`_flash_eligible` allows.  The *op-level* default is ``'dense'`` so
    that changing the runtime environment never silently switches which
    kernel a direct caller executes; the model layer
    (:mod:`bluefog_tpu.models.transformer`) opts into ``'auto'`` explicitly —
    that is the performance path, and its flash/dense parity is asserted by
    ``tests/test_flash_attention.py`` whenever a TPU is attached.
    """
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])

    eligible = _flash_eligible(q, k, causal, q_offset, k_offset)
    if backend == "flash" and not eligible:
        raise ValueError(
            "backend='flash' requires a TPU backend, Tq == Tk with T a "
            "multiple of 128, head_dim >= 32, and static equal offsets when "
            f"causal; got backend={jax.default_backend()!r}, "
            f"Tq={q.shape[1]}, Tk={k.shape[1]}, D={q.shape[-1]}, "
            f"causal={causal}, offsets=({q_offset}, {k_offset}) — the Pallas "
            "kernel has no offset mask, so forcing it here would be "
            "silently wrong")
    use_flash = backend == "flash" or (backend == "auto" and eligible)
    if use_flash:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as _flash)

        # kernel layout is (B, H, T, D)
        out = _flash(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), causal=causal, sm_scale=scale,
            block_sizes=_flash_block_sizes(q.shape[1], flash_block))
        return out.transpose(0, 2, 1, 3).astype(q.dtype)
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = k_offset + jnp.arange(k.shape[1])
        mask = qpos[:, None] >= kpos[None, :]
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


def _fold_block(state, q, k, v, *, scale, kpos0, qpos, masked: bool,
                kv_tile: int):
    """Flash-style inner step: fold one KV block into the running
    online-softmax state ``(m, denom, o)``.

    The block is processed in ``kv_tile``-sized key tiles by a ``lax.scan``
    whose body is rematerialized — the flash-attention recipe (tiled online
    softmax, O(t_q x tile) live score memory, activations recomputed in the
    backward pass) expressed in XLA-friendly form instead of a hand-written
    kernel.  ``masked=True`` applies the causal mask of global query
    positions ``qpos`` against key positions ``kpos0 + arange`` (only the
    diagonal block needs it; strictly-past blocks skip the mask entirely).
    """
    b, t_k, h, d = k.shape

    # largest divisor of t_k not exceeding kv_tile, so the promised
    # O(t_q x tile) live-score bound survives non-divisible block sizes; only
    # if nothing but degenerate divisors exist (prime-ish widths would scan
    # near-single-key tiles) does one whole-block tile beat a serial scan
    tile = min(kv_tile, t_k)
    while t_k % tile:
        tile -= 1
    if tile < min(8, t_k, kv_tile):
        tile = t_k
    nt = t_k // tile

    def fold_tile(carry, xs):
        m, denom, o = carry
        kt, vt, kt0 = xs  # (B, tile, H, D) x2, scalar global key offset
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q, kt, preferred_element_type=jnp.float32
        ) * scale
        if masked:
            kpos = kt0 + jnp.arange(tile)
            scores = jnp.where((qpos[:, None] >= kpos[None, :])[None, None],
                               scores, _NEG_INF)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[..., None])
        denom = denom * alpha + p.sum(axis=-1)
        o = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p.astype(vt.dtype), vt,
            preferred_element_type=jnp.float32,
        )
        return (m_new, denom, o), None

    if nt == 1:
        return jax.checkpoint(fold_tile)(state, (k, v, kpos0))[0]
    k_tiles = k.reshape(b, nt, tile, h, d).transpose(1, 0, 2, 3, 4)
    v_tiles = v.reshape(b, nt, tile, h, d).transpose(1, 0, 2, 3, 4)
    offs = kpos0 + tile * jnp.arange(nt)
    state, _ = lax.scan(jax.checkpoint(fold_tile), state,
                        (k_tiles, v_tiles, offs))
    return state


def _zigzag_permutation(n: int, t_total: int):
    """Global row order for the load-balanced causal layout: the sequence is
    cut into ``2n`` chunks and rank ``r`` holds chunks ``r`` and ``2n-1-r``
    (a front chunk and its mirrored back chunk)."""
    import numpy as _np

    c, rem = divmod(t_total, 2 * n)
    if rem:
        raise ValueError(
            f"zigzag layout needs sequence length divisible by 2*axis_size; "
            f"got T={t_total}, n={n}")
    order = []
    for r_ in range(n):
        order.extend(range(r_ * c, (r_ + 1) * c))
        order.extend(range((2 * n - 1 - r_) * c, (2 * n - r_) * c))
    return _np.asarray(order)


def zigzag_shard(x, axis_size: int, axis: int = 1):
    """Reorder a *global* sequence axis into the zigzag layout, so that
    contiguous sharding over ``axis_size`` ranks gives each rank a front
    chunk and its mirrored back chunk (the load-balanced causal layout)."""
    idx = _zigzag_permutation(axis_size, x.shape[axis])
    return jnp.take(x, jnp.asarray(idx), axis=axis)


def zigzag_unshard(x, axis_size: int, axis: int = 1):
    """Inverse of :func:`zigzag_shard` (restores global sequence order)."""
    import numpy as _np

    idx = _zigzag_permutation(axis_size, x.shape[axis])
    return jnp.take(x, jnp.asarray(_np.argsort(idx)), axis=axis)


def ring_attention(
    q,
    k,
    v,
    axis_name: str,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    kv_tile: int = 512,
    layout: str = "contiguous",
):
    """Blockwise ring attention over a sequence-sharded mesh axis.

    Each rank holds the blocks ``q/k/v: (B, T_local, H, D)`` of a global
    sequence of length ``n * T_local`` laid out in rank order.  KV blocks
    rotate around the ring; each arrival is folded into the running
    (max, denominator, output) online-softmax state, so the result is exactly
    full attention over the global sequence, returned sequence-sharded.

    The rotation is the same single-shift circulant permutation the gossip
    schedule produces for :class:`~bluefog_tpu.topology.RingGraph` — on TPU it
    rides the ICI torus ring, and XLA overlaps the next block's ppermute with
    the current block's attention math.

    The inner step is flash-style (:func:`_fold_block`): ``kv_tile``-sized
    online-softmax tiles with rematerialization, so a rank's live score
    buffer is ``(B, H, t_q, kv_tile)`` regardless of block size.

    For ``causal=True`` the per-step work is dispatched on the arriving
    block's position: the diagonal block (processed first, so the running max
    is finite from step 0) runs with the triangle mask, strictly-past blocks
    run unmasked, and strictly-future blocks are **skipped outright** — only
    the taken branch executes, so the causal ring does ~half the attention
    FLOPs of the non-causal one instead of computing scores and masking them
    to zero.

    ``layout`` selects how the global sequence is assumed to be distributed:

    - ``'contiguous'`` (default): rank ``r`` holds rows ``[r*T_local,
      (r+1)*T_local)``.  Causal skipping then saves total FLOPs but is
      *imbalanced* — rank 0 skips almost every block, rank n-1 none — and
      since the ring is lock-stepped by its ppermutes, on a real slice the
      per-step critical path is the busiest rank and the saving shows up as
      idle time/energy, not wall-clock.
    - ``'zigzag'``: rank ``r`` holds chunks ``r`` and ``2n-1-r`` of the
      sequence cut into ``2n`` chunks (use :func:`zigzag_shard` /
      :func:`zigzag_unshard` to convert; output stays in zigzag order).
      At step 0 every rank folds its two (half-cost) masked diagonals plus
      the always-past ``q_back x k_front`` fold; every steady-state step
      folds **exactly two half-chunks** — that same ``q_back x k_front``
      fold plus one of ``q_front x k_front`` / ``q_back x k_back`` selected
      by the arriving block's origin — so the causal FLOP saving is
      identically load-balanced across ranks and
      becomes wall-clock on a lock-stepped slice.  (Non-causal math is
      position-independent, so ``layout`` only matters for ``causal=True``.)
    """
    n = _axis_size(axis_name)
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    b, t_q, h, d = q.shape
    t_k = k.shape[1]
    if causal and t_q != t_k:
        # block classification below (past/diagonal/future by rank index)
        # presumes equal shard widths, which ring *self*-attention always has
        raise ValueError(
            f"causal ring attention requires equal q/k shard widths, got "
            f"t_q={t_q}, t_k={t_k}")
    r = lax.axis_index(axis_name)

    state = (
        jnp.full((b, h, t_q), _NEG_INF, jnp.float32),
        jnp.zeros((b, h, t_q), jnp.float32),
        jnp.zeros((b, h, t_q, d), jnp.float32),
    )
    # the skip branch of the causal dispatch returns the carry unchanged, so
    # the carry must already be marked varying over the mesh axis or branch
    # output types (VMA) disagree with the fold branches
    try:
        _mark_varying = lambda t: lax.pcast(t, axis_name, to="varying")
        state = jax.tree_util.tree_map(_mark_varying, state)
    except (AttributeError, TypeError):
        try:  # older jax: pvary
            state = jax.tree_util.tree_map(
                lambda t: lax.pvary(t, axis_name), state)
        except (AttributeError, TypeError):
            pass  # pre-VMA jax: branch output types carry no varying-axes
            # annotation, so the carry needs no marking at all

    shift = [(i, (i + 1) % n) for i in range(n)]

    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown layout {layout!r}")
    if causal and layout == "zigzag":
        return _ring_zigzag_causal(
            state, q, k, v, axis_name, n=n, r=r, scale=scale,
            kv_tile=kv_tile, shift=shift)

    qpos = r * t_q + jnp.arange(t_q)

    for s in range(n):
        src = (r - s) % n  # rank whose KV block we currently hold
        kpos0 = src * t_k
        if not causal:
            state = _fold_block(state, q, k, v, scale=scale, kpos0=kpos0,
                                qpos=qpos, masked=False, kv_tile=kv_tile)
        elif s == 0:
            # statically the diagonal block (src == r): triangle mask, and
            # the running max is finite from step 0
            state = _fold_block(state, q, k, v, scale=scale, kpos0=kpos0,
                                qpos=qpos, masked=True, kv_tile=kv_tile)
        else:
            # s > 0 never sees the diagonal again: the block is strictly
            # past (fold unmasked) or strictly future (skip outright — the
            # cond executes only the taken branch, so future blocks are free)
            state = lax.cond(
                src < r,
                lambda st, k, v, kp0: _fold_block(
                    st, q, k, v, scale=scale, kpos0=kp0, qpos=qpos,
                    masked=False, kv_tile=kv_tile),
                lambda st, k, v, kp0: st,
                state, k, v, kpos0,
            )
        if s != n - 1:
            k = lax.ppermute(k, axis_name, shift)
            v = lax.ppermute(v, axis_name, shift)

    _, denom, o = state
    out = o / jnp.maximum(denom[..., None], 1e-30)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def _ring_zigzag_causal(state, q, k, v, axis_name, *, n, r, scale, kv_tile,
                        shift):
    """Load-balanced causal ring (zigzag layout; see :func:`ring_attention`).

    Rank ``r``'s local rows are [chunk ``r``; chunk ``2n-1-r``] of the global
    sequence in ``2n`` chunks of width ``c``.  For an arriving KV block from
    rank ``src`` the four (q-chunk, k-chunk) pairs classify statically or by
    ``src`` alone:

    - ``q_front(r) x k_back(2n-1-src)``: always strictly future — never
      folded.
    - ``q_back(2n-1-r) x k_front(src)``: always strictly past — folded
      unmasked every step.
    - ``q_front x k_front`` is past iff ``src < r``; ``q_back x k_back`` is
      past iff ``src > r``; exactly one of the two per step (both diagonal at
      ``s == 0``), so every rank folds exactly two ``c``-wide chunks per
      step — balanced, half the non-causal work.
    """
    t_q = q.shape[1]
    if t_q % 2:
        raise ValueError(
            f"zigzag layout needs an even local width, got t_q={t_q}")
    c = t_q // 2
    qf, qb = q[:, :c], q[:, c:]
    rel = jnp.arange(c)  # chunk-relative positions (diagonal masks align)

    # The front and back query halves never share a fold, so carry two
    # independent half-states (m, denom, o over c rows) and join once at the
    # end — no per-fold slice/concat traffic.
    def halve(t):
        return t[..., :c], t[..., c:]

    def halve_o(t):
        return t[..., :c, :], t[..., c:, :]

    m, denom, o = state
    front = (halve(m)[0], halve(denom)[0], halve_o(o)[0])
    back = (halve(m)[1], halve(denom)[1], halve_o(o)[1])

    def fold(st, qc, kc, vc, masked):
        return _fold_block(st, qc, kc, vc, scale=scale, kpos0=0, qpos=rel,
                           masked=masked, kv_tile=kv_tile)

    for s in range(n):
        kf, kb = k[:, :c], k[:, c:]
        vf, vb = v[:, :c], v[:, c:]
        if s == 0:  # statically src == r: two diagonals + back-vs-front past
            front = fold(front, qf, kf, vf, True)
            back = fold(back, qb, kb, vb, True)
            back = fold(back, qb, kf, vf, False)
        else:
            src = (r - s) % n
            back = fold(back, qb, kf, vf, False)
            front, back = lax.cond(
                src < r,
                lambda fr, bk, kf, vf, kb, vb: (fold(fr, qf, kf, vf, False), bk),
                lambda fr, bk, kf, vf, kb, vb: (fr, fold(bk, qb, kb, vb, False)),
                front, back, kf, vf, kb, vb,
            )
        if s != n - 1:
            k = lax.ppermute(k, axis_name, shift)
            v = lax.ppermute(v, axis_name, shift)

    denom = jnp.concatenate([front[1], back[1]], axis=-1)
    o = jnp.concatenate([front[2], back[2]], axis=-2)
    out = o / jnp.maximum(denom[..., None], 1e-30)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def all_to_all_attention(
    q,
    k,
    v,
    axis_name: str,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    backend: str = "dense",
):
    """Ulysses-style sequence parallelism: reshard seq→heads, attend, reshard
    back.

    Input ``(B, T_local, H, D)`` sequence-sharded; requires ``H % n == 0``.
    Two ``lax.all_to_all`` collectives replace the ring's n-1 permutes —
    cheaper at moderate sequence lengths, while :func:`ring_attention` wins
    when T is huge or H < n.
    """
    n = _axis_size(axis_name)
    h = q.shape[2]
    if h % n != 0:
        raise ValueError(f"num_heads={h} not divisible by axis size {n}; "
                         "use ring_attention for head counts below the mesh size")

    def seq_to_heads(x):  # (B, T/n, H, D) -> (B, T, H/n, D)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):  # (B, T, H/n, D) -> (B, T/n, H, D)
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qf, kf, vf = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = local_attention(qf, kf, vf, causal=causal, scale=scale,
                          backend=backend)
    return heads_to_seq(out)
