"""Long-context sequence/context parallelism: ring attention + all-to-all.

The reference is a pre-LLM data-parallel library with no sequence dimension
(SURVEY.md §5 "long-context": absent), but its core primitive — neighbor
exchange along a ring with compute overlapped — is exactly the communication
pattern of ring attention.  This module makes long context a first-class
capability of the framework by reusing the gossip machinery's ppermute ring:

- :func:`ring_attention` — blockwise attention with the KV blocks rotating
  around the mesh axis (one ``lax.ppermute`` per step, riding the ICI ring),
  combined with a numerically stable online softmax (flash-attention-style
  running max / denominator).  Memory per device is O(T/n), enabling
  sequences n× longer than single-device attention.
- :func:`all_to_all_attention` — DeepSpeed-Ulysses-style sequence parallelism:
  ``lax.all_to_all`` resharding sequence↔heads, full local attention, and the
  inverse reshard.  Fewer collective steps than the ring (2 all-to-alls vs
  n-1 permutes) but requires ``num_heads % axis_size == 0``.

Both run inside ``shard_map`` with the sequence dimension sharded over
``axis_name``; both are jit/grad compatible (the backward pass re-runs the
rotation in reverse via XLA's transpose of ``ppermute``).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "ring_attention",
    "all_to_all_attention",
    "local_attention",
]

_NEG_INF = -1e30  # large finite negative: avoids -inf NaN traps in exp


def _flash_eligible(q, k, causal, q_offset, k_offset) -> bool:
    """Static eligibility check for the fused TPU flash kernel.

    The Pallas kernel (``jax.experimental.pallas.ops.tpu.flash_attention``)
    needs: a TPU backend, sequence length a multiple of its 128-row block,
    equal q/k lengths, and — because its causal mask is the standard aligned
    one — *static* offsets with ``q_offset == k_offset`` when causal.
    """
    if jax.default_backend() not in ("tpu", "axon"):
        return False
    if not (isinstance(q_offset, int) and isinstance(k_offset, int)):
        return False
    if causal and q_offset != k_offset:
        return False
    t_q, t_k = q.shape[1], k.shape[1]
    return t_q == t_k and t_q >= 128 and t_q % 128 == 0 and q.shape[-1] >= 32


def local_attention(q, k, v, *, causal: bool = False, scale: Optional[float] = None,
                    q_offset=0, k_offset=0, backend: str = "dense"):
    """Plain softmax attention on local blocks (also the Ulysses inner step).

    Shapes: ``q (B, Tq, H, D)``, ``k/v (B, Tk, H, D)`` → ``(B, Tq, H, D)``.
    ``q_offset``/``k_offset`` are the *global* positions of the first query /
    key row, used for causal masking of shifted blocks (may be traced).

    ``backend``: ``'dense'`` (default) materializes the (Tq, Tk) scores
    (portable, covered by CI); ``'flash'`` forces the fused Pallas TPU kernel
    (O(T) memory, fwd+bwd); ``'auto'`` picks flash whenever
    :func:`_flash_eligible` allows.  The *op-level* default is ``'dense'`` so
    that changing the runtime environment never silently switches which
    kernel a direct caller executes; the model layer
    (:mod:`bluefog_tpu.models.transformer`) opts into ``'auto'`` explicitly —
    that is the performance path, and its flash/dense parity is asserted by
    ``tests/test_flash_attention.py`` whenever a TPU is attached.
    """
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])

    eligible = _flash_eligible(q, k, causal, q_offset, k_offset)
    if backend == "flash" and not eligible:
        raise ValueError(
            "backend='flash' requires a TPU backend, Tq == Tk with T a "
            "multiple of 128, head_dim >= 32, and static equal offsets when "
            f"causal; got backend={jax.default_backend()!r}, "
            f"Tq={q.shape[1]}, Tk={k.shape[1]}, D={q.shape[-1]}, "
            f"causal={causal}, offsets=({q_offset}, {k_offset}) — the Pallas "
            "kernel has no offset mask, so forcing it here would be "
            "silently wrong")
    use_flash = backend == "flash" or (backend == "auto" and eligible)
    if use_flash:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as _flash)

        # kernel layout is (B, H, T, D)
        out = _flash(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), causal=causal, sm_scale=scale)
        return out.transpose(0, 2, 1, 3).astype(q.dtype)
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = k_offset + jnp.arange(k.shape[1])
        mask = qpos[:, None] >= kpos[None, :]
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


def ring_attention(
    q,
    k,
    v,
    axis_name: str,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
):
    """Blockwise ring attention over a sequence-sharded mesh axis.

    Each rank holds the blocks ``q/k/v: (B, T_local, H, D)`` of a global
    sequence of length ``n * T_local`` laid out in rank order.  KV blocks
    rotate around the ring; each arrival is folded into the running
    (max, denominator, output) online-softmax state, so the result is exactly
    full attention over the global sequence, returned sequence-sharded.

    The rotation is the same single-shift circulant permutation the gossip
    schedule produces for :class:`~bluefog_tpu.topology.RingGraph` — on TPU it
    rides the ICI torus ring, and XLA overlaps the next block's ppermute with
    the current block's attention math.

    For ``causal=True``, block ``j``'s keys are masked against this rank's
    global query positions; blocks strictly in the future contribute exp(-inf)
    = 0.  (The diagonal block is processed first, so the running max is finite
    from step 0.)
    """
    n = lax.axis_size(axis_name)
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    b, t_q, h, _ = q.shape
    t_k = k.shape[1]
    r = lax.axis_index(axis_name)

    m = jnp.full((b, h, t_q), _NEG_INF, jnp.float32)
    denom = jnp.zeros((b, h, t_q), jnp.float32)
    o = jnp.zeros((b, h, t_q, q.shape[-1]), jnp.float32)

    shift = [(i, (i + 1) % n) for i in range(n)]
    qpos = r * t_q + jnp.arange(t_q)

    for s in range(n):
        src = (r - s) % n  # rank whose KV block we currently hold
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
        ) * scale
        if causal:
            kpos = src * t_k + jnp.arange(t_k)
            mask = qpos[:, None] >= kpos[None, :]
            scores = jnp.where(mask[None, None], scores, _NEG_INF)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[..., None])
        denom = denom * alpha + p.sum(axis=-1)
        o = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p.astype(v.dtype), v,
            preferred_element_type=jnp.float32,
        )
        m = m_new
        if s != n - 1:
            k = lax.ppermute(k, axis_name, shift)
            v = lax.ppermute(v, axis_name, shift)

    out = o / jnp.maximum(denom[..., None], 1e-30)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def all_to_all_attention(
    q,
    k,
    v,
    axis_name: str,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    backend: str = "dense",
):
    """Ulysses-style sequence parallelism: reshard seq→heads, attend, reshard
    back.

    Input ``(B, T_local, H, D)`` sequence-sharded; requires ``H % n == 0``.
    Two ``lax.all_to_all`` collectives replace the ring's n-1 permutes —
    cheaper at moderate sequence lengths, while :func:`ring_attention` wins
    when T is huge or H < n.
    """
    n = lax.axis_size(axis_name)
    h = q.shape[2]
    if h % n != 0:
        raise ValueError(f"num_heads={h} not divisible by axis size {n}; "
                         "use ring_attention for head counts below the mesh size")

    def seq_to_heads(x):  # (B, T/n, H, D) -> (B, T, H/n, D)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):  # (B, T, H/n, D) -> (B, T/n, H, D)
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qf, kf, vf = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = local_attention(qf, kf, vf, causal=causal, scale=scale,
                          backend=backend)
    return heads_to_seq(out)
