"""Evidence: what each rank observed, disseminated coordinator-free.

Each rank's :class:`Evidence` record carries its LOCAL view of the
fleet — per-peer lag (wire: the :class:`~bluefog_tpu.runtime.
window_server.DepositStream` ack/heartbeat EWMA, which is itself kept
fresh between deposits by the heartbeat piggyback; thread mode: seconds
since the peer's last fresh deposit), per-peer health states, per-peer
reconnect deltas (lossy-link evidence), and two scalar mixing signals
(``mixing_excess``, ``consensus_growth``).  No rank sees everything —
a slow peer is observed only by the ranks that send to it — so records
are DISSEMINATED and every controller decides over the union:

- **MP mode** — the membership-record pattern (PR 6): one
  ``ctlev.<rank>`` file per rank in the shared barrier directory,
  written atomically (tmp + rename) so a reader never parses a torn
  record, newest round wins.  The barrier dir is the one medium every
  rank already polls for tombstones/membership, so evidence rides the
  same cadence for free.
- **Thread mode** — :class:`EvidenceBoard`, the in-process twin (a
  locked table, the :class:`~bluefog_tpu.runtime.resilience.
  HealthBoard` shape).

Records are canonically JSON-encoded (sorted keys): the decision
function is deterministic in the PARSED records, so two ranks that read
the same files compute byte-identical plans.
"""

from __future__ import annotations

import dataclasses
import json
import os

from bluefog_tpu.utils import lockcheck as _lc
from typing import Dict, List, Mapping, Optional, Tuple

__all__ = ["Evidence", "EvidenceBoard", "canonicalize", "write_evidence",
           "read_evidence", "clear_evidence"]

_PREFIX = "ctlev"


def _canon_map(m: Optional[Mapping[int, float]], cast) -> Dict[int, float]:
    return {int(k): cast(v) for k, v in (m or {}).items()}


def _canon_phases(m) -> Dict[int, Dict[str, float]]:
    """Canonical per-peer phase map: int peer keys, str phase keys,
    float seconds; non-finite values dropped (canonical JSON has no
    NaN spelling for nested maps)."""
    import math

    out: Dict[int, Dict[str, float]] = {}
    for k, phases in (m or {}).items():
        inner = {str(p): float(v) for p, v in (phases or {}).items()
                 if math.isfinite(float(v))}
        if inner:
            out[int(k)] = inner
    return out


@dataclasses.dataclass(frozen=True)
class Evidence:
    """One rank's round-stamped local observations.

    ``lag_s`` maps peer -> seconds of observed lag (transport-specific,
    see module docstring); ``states`` maps peer -> health-state int
    (:mod:`bluefog_tpu.runtime.resilience` values); ``reconnects`` maps
    peer -> reconnect cycles observed against that peer SINCE THE LAST
    evidence publish (a delta, not a lifetime count — so the signal
    clears when the link heals and hysteresis can release the peer).
    ``mixing_excess`` is measured-minus-predicted contraction (NaN when
    unknown); ``consensus_growth`` is local disagreement now over one
    evidence window ago (NaN until two windows exist).

    ``phase_s`` (optional — empty when tracing is off or the peer's
    connection never negotiated the trace feature) maps peer -> a phase
    decomposition of the observed lag, seconds per phase: ``"net"``
    (wire + server frontend residue), ``"queue"`` (owner apply-queue
    wait), ``"apply"`` (owner apply).  It is what lets
    :func:`~bluefog_tpu.control.controller.decide_plan` tell a slow
    LINK (net-dominated — codec/cadence territory) from a slow HOST
    (queue/apply-dominated — ring-spine penalty territory).  Records
    without it parse and decide exactly as before."""

    rank: int
    round: int
    lag_s: Mapping[int, float] = dataclasses.field(default_factory=dict)
    states: Mapping[int, int] = dataclasses.field(default_factory=dict)
    reconnects: Mapping[int, int] = dataclasses.field(default_factory=dict)
    mixing_excess: float = float("nan")
    consensus_growth: float = float("nan")
    phase_s: Mapping[int, Mapping[str, float]] = dataclasses.field(
        default_factory=dict)

    def __post_init__(self):
        object.__setattr__(self, "lag_s", _canon_map(self.lag_s, float))
        object.__setattr__(self, "states", _canon_map(self.states, int))
        object.__setattr__(self, "reconnects",
                           _canon_map(self.reconnects, int))
        object.__setattr__(self, "phase_s", _canon_phases(self.phase_s))

    def to_json(self) -> str:
        """Canonical encoding (sorted keys; NaN spelled explicitly) —
        what lands in a ``ctlev.<rank>`` record."""
        def num(x):
            return None if x != x else float(x)  # NaN -> null

        return json.dumps(
            {"rank": int(self.rank), "round": int(self.round),
             "lag_s": {str(k): float(v)
                       for k, v in sorted(self.lag_s.items())},
             "states": {str(k): int(v)
                        for k, v in sorted(self.states.items())},
             "reconnects": {str(k): int(v)
                            for k, v in sorted(self.reconnects.items())},
             "mixing_excess": num(self.mixing_excess),
             "consensus_growth": num(self.consensus_growth),
             # phase maps hold only finite floats (canonicalized), so
             # sorted-key dumping keeps the encoding byte-deterministic
             "phase_s": {str(k): {p: float(v)
                                  for p, v in sorted(m.items())}
                         for k, m in sorted(self.phase_s.items())}},
            sort_keys=True, separators=(",", ":"))

    @staticmethod
    def from_json(text: str) -> "Evidence":
        d = json.loads(text)

        def num(x):
            return float("nan") if x is None else float(x)

        return Evidence(
            rank=int(d["rank"]), round=int(d["round"]),
            lag_s={int(k): float(v) for k, v in d["lag_s"].items()},
            states={int(k): int(v) for k, v in d["states"].items()},
            reconnects={int(k): int(v)
                        for k, v in d["reconnects"].items()},
            mixing_excess=num(d.get("mixing_excess")),
            consensus_growth=num(d.get("consensus_growth")),
            # absent in pre-tracing records: they parse (and decide)
            # exactly as before
            phase_s={int(k): dict(m)
                     for k, m in d.get("phase_s", {}).items()})


def canonicalize(evidences) -> Tuple[Evidence, ...]:
    """Deterministic dedup + order: newest round per rank, sorted by
    rank.  Two ranks holding the same record MULTISET in any order
    produce the same tuple — the input normalization that makes the
    decision function order-independent."""
    best: Dict[int, Evidence] = {}
    for ev in evidences:
        cur = best.get(ev.rank)
        if cur is None or ev.round > cur.round:
            best[ev.rank] = ev
    return tuple(best[r] for r in sorted(best))


# --------------------------------------------------------------- MP records
def write_evidence(dirpath: str, ev: Evidence) -> None:
    """Atomically publish rank ``ev.rank``'s record (tmp + rename — a
    concurrent reader sees the old record or the new one, never a torn
    mix; the membership-record discipline)."""
    path = os.path.join(dirpath, f"{_PREFIX}.{int(ev.rank)}")
    with open(path + ".tmp", "w") as f:
        f.write(ev.to_json())
    os.replace(path + ".tmp", path)


def read_evidence(dirpath: str, n_ranks: int) -> List[Evidence]:
    """Every parseable evidence record in the barrier directory.  A
    missing or malformed record is skipped (a rank that has not
    published yet, or a writer caught mid-crash) — decisions are over
    whatever evidence exists, exactly like tombstone scans."""
    out: List[Evidence] = []
    for r in range(n_ranks):
        try:
            with open(os.path.join(dirpath, f"{_PREFIX}.{r}")) as f:
                out.append(Evidence.from_json(f.read()))
        except (OSError, ValueError, KeyError):
            continue
    return out


def clear_evidence(dirpath: str, rank: int) -> None:
    try:
        os.unlink(os.path.join(dirpath, f"{_PREFIX}.{int(rank)}"))
    except OSError:
        pass


# ------------------------------------------------------------- thread board
class EvidenceBoard:
    """In-process evidence table for the rank-THREAD runners: the same
    publish/collect contract as the barrier-dir records, minus the
    filesystem.  Thread-safe; newest round per rank wins."""

    def __init__(self):
        self._mu = _lc.lock("control.evidence.EvidenceBoard._mu")
        self._table: Dict[int, Evidence] = {}

    def publish(self, ev: Evidence) -> None:
        with self._mu:
            cur = self._table.get(ev.rank)
            if cur is None or ev.round >= cur.round:
                self._table[ev.rank] = ev

    def snapshot(self) -> Tuple[Evidence, ...]:
        with self._mu:
            return canonicalize(self._table.values())
