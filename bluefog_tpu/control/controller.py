"""The per-rank communication controller: evidence in, CommPlan out.

Coordinator-free by construction: :func:`decide_plan` is a PURE
function of ``(previous plan, canonicalized evidence, config, fleet
size)`` — no clock, no RNG, no rank identity — so every rank that has
seen the same disseminated records computes the byte-identical plan
(the property the plan-convergence test asserts literally), and ranks
whose record views diverge transiently reconverge as the records
propagate, exactly like tombstones and membership records do.

No-flap guarantees, stated plainly:

- **Hysteresis**: every condition that turns a knob ON is strictly
  stronger than the one that turns it OFF (``slow_enter > slow_exit``,
  ``densify_enter > densify_exit``, ``grow_hi > grow_lo``), so
  telemetry oscillating around one threshold holds the plan steady.
- **Cooldown**: after a plan change, further changes are refused until
  ``cooldown_rounds`` rounds pass — the turbulence an actuation itself
  causes (a replanned graph briefly mixes differently; a re-routed
  queue briefly drains) can never trigger the next actuation.
- **Round-boundary actuation**: :meth:`CommController.apply_plan` is
  the ONE actuation primitive, and the BF-CTL001 lint requires every
  caller to sit in a round-boundary/quiesce context — weights, cadence
  and codec change between rounds, never inside one, which is what
  keeps the exact push-sum mass audit valid through every plan change
  (a plan moves edges; it never creates or destroys mass).
"""

from __future__ import annotations

import math
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from bluefog_tpu.blackbox import recorder as _bb
from bluefog_tpu.control.evidence import Evidence, canonicalize
from bluefog_tpu.control.plan import CODEC_LADDER, CommPlan, ControlConfig
from bluefog_tpu.metrics import comm as _mt
from bluefog_tpu.metrics.registry import median as _reg_median
from bluefog_tpu.topology.graphs import Topology, replan_penalized

# the resilience health-state values SUSPECT/DEAD, spelled locally so
# this package stays import-leaf (bluefog_tpu.runtime imports control's
# consumers; importing runtime back from here would be a cycle).  The
# pairing is asserted by a test against the canonical constants.
_ST_SUSPECT = 1
_ST_DEAD = 2

__all__ = ["CommController", "decide_plan", "plan_topology"]


def _median(vals: Sequence[float]) -> float:
    # the shared interpolating median, with this module's empty-input
    # convention preserved (0.0, not NaN: an empty lag table must read
    # as "no lag evidence", never poison the threshold arithmetic)
    if not vals:
        return 0.0
    return _reg_median(vals)


def _peer_lag(evidences: Sequence[Evidence]) -> Dict[int, float]:
    """Per-peer consensus lag over all reporters: the MEDIAN of what
    the ranks that actually touch the peer observed (median, not max —
    one confused reporter must not convict a healthy peer)."""
    seen: Dict[int, List[float]] = {}
    for ev in evidences:
        for j, v in ev.lag_s.items():
            if math.isfinite(v):
                seen.setdefault(int(j), []).append(float(v))
    return {j: _median(vs) for j, vs in seen.items()}


def _peer_net_frac(evidences: Sequence[Evidence]) -> Dict[int, float]:
    """Per-peer fraction of the traced lag spent on the WIRE (the
    ``net`` phase) rather than in the owner's queue/apply — median per
    phase over the reporters that carried phase evidence, then
    normalized.  Empty when tracing is off fleet-wide, so pre-tracing
    evidence decides exactly as before."""
    acc: Dict[int, Dict[str, List[float]]] = {}
    for ev in evidences:
        for j, m in ev.phase_s.items():
            per = acc.setdefault(int(j), {})
            for p, v in m.items():
                if math.isfinite(v):
                    per.setdefault(str(p), []).append(float(v))
    out: Dict[int, float] = {}
    for j, per in acc.items():
        med = {p: _median(vs) for p, vs in per.items()}
        total = sum(med.values())
        if total > 0:
            out[j] = med.get("net", 0.0) / total
    return out


def decide_plan(prev: CommPlan, round_: int,
                evidences: Iterable[Evidence],
                cfg: ControlConfig) -> CommPlan:
    """The deterministic decision table (see docs/control.md) — a pure
    function of exactly ``(prev, round_, evidences, cfg)``; the live
    fleet size is derived from the reporter count the records
    themselves carry.

    Returns ``prev`` unchanged (same object, same version) when nothing
    crosses a threshold or the cooldown is still running; otherwise a
    new plan with ``version = prev.version + 1`` stamped ``round_``.
    """
    evs = canonicalize(evidences)
    if not evs:
        return prev
    # cooldown: a fresh plan is immune until it has had time to act
    if prev.version > 0 and round_ < prev.round + cfg.cooldown_rounds:
        return prev

    # ---- slow set (hysteresis band around the fleet-median lag) ----
    lag = _peer_lag(evs)
    fleet = _median(list(lag.values()))
    enter = max(cfg.min_lag_s, cfg.slow_enter * fleet)
    exit_ = max(cfg.min_lag_s, cfg.slow_exit * fleet)
    recon: Dict[int, int] = {}
    suspect_votes: Dict[int, int] = {}
    for ev in evs:
        for j, c in ev.reconnects.items():
            recon[j] = recon.get(j, 0) + int(c)
        for j, st in ev.states.items():
            if st in (_ST_SUSPECT, _ST_DEAD):
                suspect_votes[j] = suspect_votes.get(j, 0) + 1
    # traced phase evidence splits slow LINK from slow HOST: the codec
    # can only divert a link-slow peer while it has headroom below the
    # configured ceiling (otherwise the spine penalty is the fallback
    # remedy — a convicted peer always gets SOME remedy)
    net_frac = _peer_net_frac(evs)
    base_codec = min(prev.codec_level, cfg.max_codec_level)
    codec_headroom = base_codec < cfg.max_codec_level
    diverted: List[int] = []
    slow: List[int] = []
    for j in sorted(set(lag) | set(recon) | set(suspect_votes)):
        was = j in prev.slow
        lat = lag.get(j, 0.0)
        lossy = recon.get(j, 0) >= cfg.reconnects_enter
        # a MAJORITY of reporters holding the peer SUSPECT/DEAD is
        # entry evidence in its own right (a wedged peer can have an
        # unremarkable ack EWMA — the last ack before the wedge was
        # fast); ANY suspicion holds an already-penalized peer in
        suspected = (suspect_votes.get(j, 0) * 2
                     >= max(1, len(evs)))
        if was:
            # release only when EVERY signal cleared: lag below the
            # exit band, a quiet wire, and nobody suspicious
            if (lat >= exit_ or recon.get(j, 0) > 0
                    or suspect_votes.get(j, 0) > 0):
                slow.append(j)
        elif lat >= enter or lossy or suspected:
            # a pure-lag conviction whose traced decomposition says the
            # time is on the WIRE (net-dominated) is a slow LINK:
            # compress harder instead of ring-spining the peer — the
            # host is keeping up, the bytes are not.  Reconnect/
            # suspicion evidence stays spine territory (a flapping or
            # wedged peer is not fixed by a smaller payload).
            if (not lossy and not suspected and codec_headroom
                    and net_frac.get(j, 0.0) >= cfg.link_net_frac):
                diverted.append(j)
            else:
                slow.append(j)
    # degrade links, never dissolve the fleet: keep at most
    # max_slow_frac of the LIVE fleet penalized (reporter count is the
    # live-member proxy the records themselves carry — capacity would
    # let a shrunk elastic fleet be penalized wholesale), worst lag
    # first (rank breaks ties deterministically), always allowing one
    cap = max(1, int(len(evs) * cfg.max_slow_frac))
    if len(slow) > cap:
        slow = sorted(sorted(slow, key=lambda j: (-lag.get(j, 0.0), j))[:cap])

    # ---- densify ladder on mixing excess ----
    excesses = [ev.mixing_excess for ev in evs
                if math.isfinite(ev.mixing_excess)]
    densify = prev.densify
    if excesses:
        worst = max(excesses)
        if worst > cfg.densify_enter:
            densify += 1
        elif worst < cfg.densify_exit:
            densify -= 1
    densify = max(0, densify)
    # size-aware top rung: the reporter count is the live-member proxy
    # the records themselves carry; above cfg.densify_full_max the
    # one-step exact averager (a million-edge plan at 1024 ranks) is
    # capped away and sustained excess tops out at the
    # symmetric-exponential rung — which is what lets a fleet-scale run
    # keep the ladder ENABLED (the partition scenario used to have to
    # configure it off entirely)
    if len(evs) > cfg.densify_full_max:
        densify = min(densify, 1)

    # ---- cadence + codec on the consensus-growth band ----
    growths = [ev.consensus_growth for ev in evs
               if math.isfinite(ev.consensus_growth)]
    gossip_every = prev.gossip_every
    codec_level = min(prev.codec_level, cfg.max_codec_level)
    if growths:
        worst = max(growths)
        if worst > cfg.grow_hi:
            # consensus distance is GROWING: gossip more, compress less
            gossip_every = max(1, gossip_every // 2)
            codec_level = max(0, codec_level - 1)
        elif worst < cfg.grow_lo:
            # consensus is contracting comfortably: spend less wire —
            # stretch cadence only while links are actually under
            # pressure (a slow set exists), re-arm compression toward
            # the configured ceiling
            if slow:
                gossip_every = min(cfg.cadence_max, gossip_every * 2)
            codec_level = min(cfg.max_codec_level, codec_level + 1)
    if diverted:
        # the link-slow diversion must deliver an ACTUAL remedy: the
        # plan's codec has to end up above where it started.  When the
        # growth band just backed the codec off (compression error is
        # suspect), compressing harder would fight that decision —
        # the spine is the fallback, so a convicted peer always gets
        # SOME remedy either way.
        bumped = min(cfg.max_codec_level, codec_level + 1)
        if codec_level > base_codec:
            pass  # the grow_lo re-arm already raised it
        elif bumped > base_codec:
            codec_level = bumped
        else:
            slow = sorted(set(slow) | set(diverted))
            if len(slow) > cap:
                slow = sorted(sorted(
                    slow, key=lambda j: (-lag.get(j, 0.0), j))[:cap])

    cand = CommPlan(version=prev.version + 1, round=round_,
                    slow=tuple(slow), densify=densify,
                    gossip_every=gossip_every, codec_level=codec_level)
    if (cand.slow == prev.slow and cand.densify == prev.densify
            and cand.gossip_every == prev.gossip_every
            and cand.codec_level == prev.codec_level):
        return prev
    return cand


def plan_topology(base: Topology, members, plan: CommPlan) -> Topology:
    """The mixing graph a plan prescribes over the CURRENT member set:
    the penalized deterministic rebuild (slow peers reduced to the ring
    spine, densify ladder applied).  Pure and deterministic in
    ``(base.size, sorted(members), plan)`` — the topology half of the
    every-rank-converges contract."""
    mem = sorted(members)
    return replan_penalized(base, mem,
                            slow=[r for r in plan.slow if r in set(mem)],
                            densify=plan.densify)


class CommController:
    """Per-rank controller: accumulates local telemetry, snapshots it
    as an :class:`Evidence` record for dissemination, folds the
    disseminated records into a :class:`CommPlan` via
    :func:`decide_plan`, and actuates through :meth:`apply_plan`.

    The loop contract (both async dsgd runners):

    1. every round: feed per-peer observations (:meth:`note_peer`) and
       the round's local disagreement (:meth:`note_disagreement`);
    2. every ``cfg.evidence_every`` rounds, AT A ROUND BOUNDARY:
       publish :meth:`evidence`, collect the fleet's records, call
       :meth:`decide`; when the version advanced, actuate via
       :meth:`apply_plan` (new mixing topology back to the caller, plus
       cadence/codec for the caller to install) — all before the next
       round's deposits leave.
    """

    def __init__(self, rank: int, n_ranks: int, *,
                 config: Optional[ControlConfig] = None):
        self.rank = int(rank)
        self.n = int(n_ranks)
        self.cfg = config or ControlConfig()
        # version 0 IS the launch config: codec starts at the caller's
        # ceiling (the controller backs OFF from there), everything
        # else at the static defaults
        self.plan = CommPlan(codec_level=self.cfg.max_codec_level)
        self.plan_changes = 0
        self._lag: Dict[int, float] = {}
        self._states: Dict[int, int] = {}
        self._alerts: Dict[int, int] = {}  # externally-asserted states
        self._phase: Dict[int, Dict[str, float]] = {}
        self._recon_seen: Dict[int, int] = {}   # lifetime counts per peer
        self._recon_delta: Dict[int, int] = {}  # since last evidence()
        self._mixing_excess = float("nan")
        self._dis_now: Optional[float] = None
        self._dis_prev_window: Optional[float] = None

    # ------------------------------------------------------- local feeds
    def note_peer(self, peer: int, *, lag_s: Optional[float] = None,
                  state: Optional[int] = None,
                  reconnects_total: Optional[int] = None,
                  phase_s: Optional[Dict[str, float]] = None) -> None:
        """Fold one peer observation in.  ``lag_s`` is transport lag
        (wire ack EWMA / thread staleness age); ``reconnects_total`` is
        the stream's LIFETIME count — the controller differences it
        into the per-window delta the evidence record carries;
        ``phase_s`` is the traced wire-phase decomposition of that lag
        (``{"net": s, "queue": s, "apply": s}`` from
        :meth:`~bluefog_tpu.runtime.window_server.DepositStream.
        phase_ewma`; None when tracing is off — the evidence then
        carries no breakdown and :func:`decide_plan` falls back to the
        phase-blind table)."""
        j = int(peer)
        if lag_s is not None and math.isfinite(lag_s):
            self._lag[j] = float(lag_s)
        if state is not None:
            self._states[j] = int(state)
        if phase_s:
            self._phase[j] = {str(p): float(v)
                              for p, v in phase_s.items()
                              if math.isfinite(float(v))}
        if reconnects_total is not None:
            seen = self._recon_seen.get(j, 0)
            if reconnects_total > seen:
                self._recon_delta[j] = (self._recon_delta.get(j, 0)
                                        + int(reconnects_total - seen))
                self._recon_seen[j] = int(reconnects_total)

    def note_alert(self, peer: int, *, suspect: bool = True) -> None:
        """Fold an EXTERNAL alert about ``peer`` into the states
        evidence channel — the fleet SLO engine's straggler/silent
        WARN naming a rank (:meth:`bluefog_tpu.fleet.SLOEngine.
        suspect_ranks`) is consumable by the controller exactly like a
        transport health state: while the alert stands, this rank's
        evidence records hold the peer SUSPECT (merged as max with the
        transport state, never downgrading it), and a majority of
        alerting reporters is slow-set entry evidence in its own right.
        ``suspect=False`` RETRACTS the assertion (the alert cleared);
        retraction is explicit because alerts carry their own
        hysteresis — the evidence channel must not decay what the SLO
        engine still asserts."""
        j = int(peer)
        if suspect:
            self._alerts[j] = _ST_SUSPECT
        else:
            self._alerts.pop(j, None)

    def forget_peer(self, peer: int) -> None:
        """Drop every sticky observation about ``peer`` — owed whenever
        the peer leaves this rank's observation surface (it died, it
        drained, or the plan dropped the edge this rank observed it
        through).  Without this, a frozen last observation would be
        republished in every future evidence record: a corpse's DEAD
        state keeps voting, and a recovered peer whose old reporters
        stopped refreshing could never be released by hysteresis."""
        j = int(peer)
        self._lag.pop(j, None)
        self._states.pop(j, None)
        self._alerts.pop(j, None)
        self._phase.pop(j, None)
        self._recon_delta.pop(j, None)
        self._recon_seen.pop(j, None)

    def retain_peers(self, peers) -> None:
        """Keep observations only for ``peers`` (the current
        observation surface); forget everyone else."""
        keep = {int(j) for j in peers}
        for j in (set(self._lag) | set(self._states) | set(self._phase)
                  | set(self._recon_seen)) - keep:
            alert = self._alerts.get(j)
            self.forget_peer(j)
            if alert is not None:
                # an externally-asserted alert (note_alert) outlives the
                # observation surface: a fleet SLO can name a rank this
                # rank no longer touches, and only the asserter's
                # explicit retraction — or the peer's death/leave via a
                # DIRECT forget_peer — releases it (alerts carry their
                # own hysteresis; the surface sweep must not decay them)
                self._alerts[j] = alert

    def note_disagreement(self, value: float) -> None:
        """This round's local disagreement (||z_in - z_self|| over the
        consumed neighbor mass): an EWMA feeds the consensus-growth
        signal."""
        if not math.isfinite(value):
            return
        a = self.cfg.ewma_alpha
        self._dis_now = (value if self._dis_now is None
                         else a * value + (1.0 - a) * self._dis_now)

    def note_mixing_excess(self, value: Optional[float]) -> None:
        self._mixing_excess = (float("nan") if value is None
                               else float(value))

    @property
    def disagreement(self) -> Optional[float]:
        """The current local-disagreement EWMA (what the loop feeds its
        MixingTracker each evidence window); None before the first
        fresh neighbor mass arrived."""
        return self._dis_now

    # ----------------------------------------------------- dissemination
    def evidence(self, round_: int) -> Evidence:
        """Snapshot local observations as this rank's record (and roll
        the consensus-growth window: growth compares the disagreement
        EWMA now against the previous evidence snapshot's)."""
        growth = float("nan")
        if (self._dis_now is not None
                and self._dis_prev_window is not None
                and self._dis_prev_window > 0):
            growth = self._dis_now / self._dis_prev_window
        states = dict(self._states)
        for j, st in self._alerts.items():
            # merged as MAX: an alert can raise a peer to SUSPECT but
            # never downgrade what the transport itself observed
            states[j] = max(states.get(j, 0), st)
        ev = Evidence(rank=self.rank, round=int(round_),
                      lag_s=dict(self._lag), states=states,
                      reconnects=dict(self._recon_delta),
                      mixing_excess=self._mixing_excess,
                      consensus_growth=growth,
                      phase_s={j: dict(m)
                               for j, m in self._phase.items()})
        self._dis_prev_window = self._dis_now
        self._recon_delta = {}
        return ev

    # ----------------------------------------------------------- decide
    def decide(self, round_: int,
               evidences: Iterable[Evidence]) -> CommPlan:
        """Fold the disseminated records into the current plan.  Pure
        delegation to :func:`decide_plan`; records the change in the
        flight recorder + gauges when the version advances."""
        plan = decide_plan(self.plan, int(round_), evidences, self.cfg)
        if plan.version != self.plan.version:
            self.plan_changes += 1
            _mt.inc("bf_ctl_plan_changes_total", 1.0)
            _bb.record("ctl_plan", rank=self.rank, version=plan.version,
                       round=plan.round, slow=list(plan.slow),
                       densify=plan.densify,
                       gossip_every=plan.gossip_every,
                       codec=plan.codec or "none")
        self.plan = plan
        return plan

    # ---------------------------------------------------------- actuate
    def apply_plan(self, *, topology: Topology, members) -> Topology:
        """THE actuation primitive — call ONLY from a round-boundary /
        quiesce context (nothing of this rank's in flight that the old
        plan's audit still counts on; the BF-CTL001 lint enforces the
        call-site discipline).  Returns the plan's mixing topology over
        ``members``; the caller installs it (out-neighbors, split
        fraction) together with the plan's cadence and codec before the
        next round's deposits leave."""
        plan = self.plan
        topo = plan_topology(topology, members, plan)
        _mt.set("bf_ctl_plan_version", float(plan.version))
        _mt.set("bf_ctl_slow_peers", float(len(plan.slow)))
        _mt.set("bf_ctl_gossip_every", float(plan.gossip_every))
        _mt.set("bf_ctl_codec_level", float(plan.codec_level))
        _bb.record("ctl_actuate", rank=self.rank, version=plan.version,
                   round=plan.round, topology=topo.name,
                   gossip_every=plan.gossip_every,
                   codec=plan.codec or "none")
        return topo
