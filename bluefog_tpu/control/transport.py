"""Transport autotune: the stripe-count / coalescing-cap plan family.

The striped DCN path (:class:`bluefog_tpu.runtime.window_server.
StripedDepositStream`) exposes two raw-speed knobs the static config
froze at launch:

- **stripes** — parallel per-peer TCP streams (senders, connections,
  server-side appliers).  More stripes buy line rate when the WIRE is
  the bottleneck; past that they only buy scheduler churn.
- **coalesce_bytes** — each stripe's per-frame coalescing cap.  Smaller
  frames deepen the pipeline (more frames in flight); larger frames
  amortize acks.

This module is the deciding half of the closed loop, in the exact shape
of :func:`bluefog_tpu.control.controller.decide_plan` /
:func:`bluefog_tpu.control.tree.decide_tree_plan`: a PURE, deterministic
function of the evidence the deposit streams already collect — the
per-peer ack-latency EWMA and the {net, queue, apply} phase EWMA — with
enter/exit hysteresis bands and a cooldown, emitting a round-stamped
:class:`TransportPlan` whose canonical bytes make convergence checkable
by literal equality.  Actuation happens ONLY through
``StripedDepositStream.apply_plan`` at a round boundary (the BF-CTL001
lint holds the call sites to round-boundary vocabulary, like every
other plan).

The decision table:

- **widen** (stripes x2, coalesce /2) when the ack EWMA sits above
  ``widen_enter_s`` AND the phase split says the wire is the problem
  (net fraction >= ``net_frac_enter``, or no phase evidence at all —
  an untraced connection's slow acks are still slow).  A slow OWNER
  (queue/apply-dominated) is NOT widened into: more stripes would just
  queue more at the same busy host.
- **narrow** (stripes /2, coalesce x2) when the ack EWMA is below
  ``widen_exit_s`` and more than the minimum stripes are open —
  reclaiming connections when the wire is comfortably fast.
- anything between the bands, inside the cooldown, or already at the
  caps: return ``prev`` UNCHANGED (same object, same version) — the
  no-flap contract the property tests pin.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, Optional

__all__ = ["TransportPlan", "TransportConfig", "decide_transport_plan"]


@dataclasses.dataclass(frozen=True)
class TransportPlan:
    """One round-stamped transport plan.

    Attributes:
      version: monotone plan number; 0 is the static launch config.
      round: the decision round — actuation at the first round boundary
        at or after it, never mid-round.
      stripes: parallel per-peer deposit streams to hold open.
      coalesce_bytes: per-stripe frame coalescing cap (bytes).
    """

    version: int = 0
    round: int = 0
    stripes: int = 1
    coalesce_bytes: int = 16 << 20

    def __post_init__(self):
        object.__setattr__(self, "stripes", max(1, int(self.stripes)))
        object.__setattr__(self, "coalesce_bytes",
                           max(1 << 16, int(self.coalesce_bytes)))

    def to_bytes(self) -> bytes:
        """Canonical encoding (sorted keys, normalized ints): two ranks
        that derived the same plan produce IDENTICAL bytes."""
        return json.dumps(
            {"version": int(self.version), "round": int(self.round),
             "stripes": int(self.stripes),
             "coalesce_bytes": int(self.coalesce_bytes)},
            sort_keys=True, separators=(",", ":")).encode()

    @staticmethod
    def from_bytes(blob: bytes) -> "TransportPlan":
        d = json.loads(blob.decode())
        return TransportPlan(version=int(d["version"]),
                             round=int(d["round"]),
                             stripes=int(d["stripes"]),
                             coalesce_bytes=int(d["coalesce_bytes"]))


@dataclasses.dataclass(frozen=True)
class TransportConfig:
    """Hysteresis bands + caps for :func:`decide_transport_plan`.

    Every threshold is an enter/exit PAIR with enter strictly stronger
    than exit (validated here), so evidence oscillating around one
    threshold cannot flap the plan; ``cooldown_rounds`` additionally
    freezes a changed plan until the turbulence the change itself
    causes has settled.
    """

    stripes_min: int = 1
    stripes_max: int = 8
    coalesce_min_bytes: int = 1 << 18
    coalesce_max_bytes: int = 16 << 20
    #: widen when the peer's ack EWMA exceeds this ...
    widen_enter_s: float = 0.050
    #: ... narrow only once it is back below this (enter > exit)
    widen_exit_s: float = 0.020
    #: widen only when net's share of the ack latency is at least this
    net_frac_enter: float = 0.5
    #: a net share at or below this blocks widening outright even above
    #: widen_enter_s (the slow-HOST case; enter > exit keeps the gap)
    net_frac_exit: float = 0.3
    cooldown_rounds: int = 16

    def __post_init__(self):
        if not (1 <= self.stripes_min <= self.stripes_max):
            raise ValueError(
                f"need 1 <= stripes_min <= stripes_max, got "
                f"{self.stripes_min}/{self.stripes_max}")
        if not (0 < self.coalesce_min_bytes <= self.coalesce_max_bytes):
            raise ValueError(
                f"need 0 < coalesce_min <= coalesce_max, got "
                f"{self.coalesce_min_bytes}/{self.coalesce_max_bytes}")
        if not (self.widen_enter_s > self.widen_exit_s > 0):
            raise ValueError(
                f"hysteresis: need widen_enter_s > widen_exit_s > 0, "
                f"got {self.widen_enter_s}/{self.widen_exit_s}")
        if not (1 >= self.net_frac_enter > self.net_frac_exit >= 0):
            raise ValueError(
                f"hysteresis: need 1 >= net_frac_enter > net_frac_exit "
                f">= 0, got {self.net_frac_enter}/{self.net_frac_exit}")
        if self.cooldown_rounds < 0:
            raise ValueError("cooldown_rounds must be >= 0")


def _net_frac(phase_s: Optional[Dict[str, float]]) -> Optional[float]:
    if phase_s is None:
        return None
    total = (phase_s.get("net", 0.0) + phase_s.get("queue", 0.0)
             + phase_s.get("apply", 0.0))
    if total <= 0:
        return None
    return phase_s.get("net", 0.0) / total


def decide_transport_plan(prev: TransportPlan, round_: int, *,
                          ack_ewma_s: Optional[float],
                          phase_s: Optional[Dict[str, float]] = None,
                          cfg: TransportConfig = TransportConfig(),
                          ) -> TransportPlan:
    """PURE decision step: previous plan + this round's wire evidence ->
    the plan in effect from the next round boundary.  Returns ``prev``
    ITSELF (no version bump) whenever nothing crosses a band, the
    cooldown is running, or the knobs are already at their caps —
    byte-stability of the no-change case is part of the contract."""
    if ack_ewma_s is None:
        return prev  # no wire evidence yet: never tune blind
    if (prev.version > 0
            and round_ - prev.round < cfg.cooldown_rounds):
        return prev
    frac = _net_frac(phase_s)
    if ack_ewma_s > cfg.widen_enter_s and (frac is None
                                           or frac >= cfg.net_frac_enter):
        stripes = min(cfg.stripes_max, max(cfg.stripes_min,
                                           prev.stripes * 2))
        coalesce = max(cfg.coalesce_min_bytes, prev.coalesce_bytes // 2)
        if (stripes, coalesce) == (prev.stripes, prev.coalesce_bytes):
            return prev  # already at the caps: saturated, not flapping
        return TransportPlan(version=prev.version + 1, round=round_,
                             stripes=stripes, coalesce_bytes=coalesce)
    if ack_ewma_s < cfg.widen_exit_s:
        stripes = max(cfg.stripes_min, prev.stripes // 2)
        coalesce = min(cfg.coalesce_max_bytes, prev.coalesce_bytes * 2)
        if (stripes, coalesce) == (prev.stripes, prev.coalesce_bytes):
            return prev
        return TransportPlan(version=prev.version + 1, round=round_,
                             stripes=stripes, coalesce_bytes=coalesce)
    return prev
