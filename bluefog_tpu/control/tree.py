"""The read tree's communication plan: degree, depth, delta cadence.

The control plane's discipline (docs/control.md), applied to the relay
tree: telemetry becomes canonical evidence, a PURE deterministic
function folds evidence into a round-stamped plan, and the plan is
actuated only at round boundaries (BF-CTL001, through
:meth:`~bluefog_tpu.relay.node.RelayNode.apply_plan`).  Every node that
has seen the same evidence computes the byte-identical
:class:`TreePlan` (:meth:`TreePlan.to_bytes` is canonical), so a tree
re-shape needs no coordinator — exactly the
:func:`~bluefog_tpu.control.controller.decide_plan` contract, one tier
up the read path.

The decision table, stated plainly (all thresholds hysteresis PAIRS,
all changes cooldown-limited):

- **degree** (fan-out per node): the worst per-node skip rate is the
  overload signal — a node whose readers skip more than
  ``skip_enter`` of their due rounds is pushing wider than its wire
  can carry, so degree halves; it re-doubles toward ``degree_max``
  only below ``skip_exit``.
- **depth** (relay tiers): grown when total subscriber demand exceeds
  what ``degree^(depth+1)`` leaves can absorb (readers per leaf above
  ``fan_enter``), shrunk below ``fan_exit`` — a tier costs one hop of
  staleness, so the tree is never deeper than demand requires.
- **full_every** (the delta resync-anchor cadence): worst observed
  per-tier staleness above ``stale_enter`` rounds halves it (tighter
  anchors, faster resync after gaps); below ``stale_exit`` it doubles
  toward ``full_every_max`` (spend less wire when the tree is fresh).
"""

from __future__ import annotations

import dataclasses
import json
import math
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = ["TreePlan", "TreeConfig", "TreeEvidence", "decide_tree_plan",
           "tree_capacity"]


@dataclasses.dataclass(frozen=True)
class TreePlan:
    """One round-stamped read-tree plan.

    Attributes:
      version: monotone plan number; 0 is the static launch config.
      round: the decision round — actuation happens at the first round
        boundary at or after it (BF-CTL001 call-site discipline).
      degree: max children (subscriptions) per node; the server-side
        fan-out admission limit.
      depth: relay tiers below the trainer (0 = direct fan-out).
      full_every: delta anchor cadence of every push channel (1 = every
        push full, deltas off).
    """

    version: int = 0
    round: int = 0
    degree: int = 8
    depth: int = 1
    full_every: int = 8

    def __post_init__(self):
        object.__setattr__(self, "degree", max(2, int(self.degree)))
        object.__setattr__(self, "depth", max(0, int(self.depth)))
        object.__setattr__(self, "full_every",
                           max(1, int(self.full_every)))

    def to_bytes(self) -> bytes:
        """Canonical byte encoding (sorted keys, normalized ints): two
        nodes that derived the same plan produce IDENTICAL bytes — the
        same literal-byte-equality convergence contract as
        :meth:`~bluefog_tpu.control.plan.CommPlan.to_bytes`."""
        return json.dumps(
            {"version": int(self.version), "round": int(self.round),
             "degree": int(self.degree), "depth": int(self.depth),
             "full_every": int(self.full_every)},
            sort_keys=True, separators=(",", ":")).encode()

    @staticmethod
    def from_bytes(blob: bytes) -> "TreePlan":
        d = json.loads(blob.decode())
        return TreePlan(version=int(d["version"]), round=int(d["round"]),
                        degree=int(d["degree"]), depth=int(d["depth"]),
                        full_every=int(d["full_every"]))


@dataclasses.dataclass(frozen=True)
class TreeConfig:
    """Knobs for the tree controller.  Every threshold is an enter/exit
    hysteresis pair (enter strictly stronger), and plan changes are
    rate-limited by ``cooldown_rounds`` — the
    :class:`~bluefog_tpu.control.plan.ControlConfig` no-flap posture."""

    degree_max: int = 16
    degree_min: int = 2
    depth_max: int = 4
    # per-leaf-node subscriber load that grows/shrinks the tree
    fan_enter: float = 0.9   # fraction of degree capacity in use
    fan_exit: float = 0.3
    # per-node skip-rate band (skipped / due rounds)
    skip_enter: float = 0.25
    skip_exit: float = 0.05
    # per-tier staleness band (rounds)
    stale_enter: float = 4.0
    stale_exit: float = 1.0
    full_every_max: int = 32
    cooldown_rounds: int = 16

    def __post_init__(self):
        if not (2 <= self.degree_min <= self.degree_max):
            raise ValueError("need 2 <= degree_min <= degree_max")
        if self.depth_max < 0:
            raise ValueError("depth_max must be >= 0")
        if not (self.fan_exit < self.fan_enter):
            raise ValueError("hysteresis requires fan_exit < fan_enter")
        if not (self.skip_exit < self.skip_enter):
            raise ValueError(
                "hysteresis requires skip_exit < skip_enter")
        if not (self.stale_exit < self.stale_enter):
            raise ValueError(
                "hysteresis requires stale_exit < stale_enter")
        if self.full_every_max < 1:
            raise ValueError("full_every_max must be >= 1")
        if self.cooldown_rounds < 1:
            raise ValueError("cooldown_rounds must be >= 1")


@dataclasses.dataclass(frozen=True)
class TreeEvidence:
    """One node's disseminated read-path record.

    ``subscribers`` is the node's live subscription count
    (``bf_subscribers``); ``skip_rate`` its readers' skipped/due ratio
    over the window (``bf_sub_skipped_rounds_total`` differenced);
    ``staleness_rounds`` the worst ``bf_snapshot_age_rounds{tier=}`` it
    observed.  NaN = no evidence for that signal."""

    node: str
    tier: int = 0
    subscribers: int = 0
    skip_rate: float = float("nan")
    staleness_rounds: float = float("nan")


def canonicalize_tree(evidences: Iterable[TreeEvidence]
                      ) -> List[TreeEvidence]:
    """Sorted, deduplicated (newest-listed wins per node) evidence —
    the canonical input ordering that makes :func:`decide_tree_plan`
    order-independent."""
    by_node: Dict[str, TreeEvidence] = {}
    for ev in evidences:
        by_node[str(ev.node)] = ev
    return [by_node[k] for k in sorted(by_node)]


def tree_capacity(degree: int, depth: int) -> int:
    """Leaf-subscription capacity of a ``degree``-ary tree ``depth``
    tiers deep: ``degree ** (depth + 1)`` (every tier multiplies the
    trainer's direct fan-out)."""
    return int(degree) ** (int(depth) + 1)


def decide_tree_plan(prev: TreePlan, round_: int,
                     evidences: Iterable[TreeEvidence],
                     cfg: TreeConfig) -> TreePlan:
    """The deterministic tree decision table — a pure function of
    exactly ``(prev, round_, evidences, cfg)``; returns ``prev``
    unchanged when nothing crosses a threshold or the cooldown is still
    running, otherwise a new plan with ``version = prev.version + 1``
    stamped ``round_``."""
    evs = canonicalize_tree(evidences)
    if not evs:
        return prev
    if prev.version > 0 and round_ < prev.round + cfg.cooldown_rounds:
        return prev

    demand = sum(max(0, int(ev.subscribers)) for ev in evs)
    skips = [ev.skip_rate for ev in evs
             if math.isfinite(ev.skip_rate)]
    stales = [ev.staleness_rounds for ev in evs
              if math.isfinite(ev.staleness_rounds)]

    # ---- degree on the skip-rate band ----
    degree = prev.degree
    if skips:
        worst = max(skips)
        if worst > cfg.skip_enter:
            degree = max(cfg.degree_min, degree // 2)
        elif worst < cfg.skip_exit:
            degree = min(cfg.degree_max, degree * 2)
    degree = max(cfg.degree_min, min(cfg.degree_max, degree))

    # ---- depth on subscriber demand vs capacity ----
    depth = prev.depth
    if demand > cfg.fan_enter * tree_capacity(degree, depth):
        depth += 1
    elif depth > 0 and demand < cfg.fan_exit * tree_capacity(
            degree, depth - 1):
        # the SHALLOWER tree must already absorb the demand comfortably
        # before a tier is removed — a tier costs a hop of staleness,
        # but removing one under load would overload every survivor
        depth -= 1
    depth = max(0, min(cfg.depth_max, depth))

    # ---- delta anchor cadence on the staleness band ----
    full_every = prev.full_every
    if stales:
        worst = max(stales)
        if worst > cfg.stale_enter:
            full_every = max(1, full_every // 2)
        elif worst < cfg.stale_exit:
            full_every = min(cfg.full_every_max, full_every * 2)

    cand = TreePlan(version=prev.version + 1, round=round_,
                    degree=degree, depth=depth, full_every=full_every)
    if (cand.degree == prev.degree and cand.depth == prev.depth
            and cand.full_every == prev.full_every):
        return prev
    return cand
