"""Self-tuning communication control plane: telemetry in, behavior out.

Every knob this repo exposes — topology degree, gossip cadence, wire
compression — was frozen at launch until this package: one injected
slow peer or lossy link degraded the whole fleet to the worst link's
pace (the Bluefog premise, arXiv:2111.04287, is the opposite: progress
THROUGH heterogeneity).  This package closes the loop from the
observability layers (metrics PR 2, blackbox PR 3, resilience PR 5) to
runtime behavior:

- :class:`~bluefog_tpu.control.controller.CommController` — a per-rank
  controller consuming the telemetry the runtime already produces
  (:class:`~bluefog_tpu.metrics.health.MixingTracker` measured-vs-
  predicted contraction, consensus disagreement, peer health states,
  the :class:`~bluefog_tpu.runtime.window_server.DepositStream` ack
  EWMA + reconnect counters) and emitting a round-stamped
  :class:`~bluefog_tpu.control.plan.CommPlan`;
- evidence DISSEMINATION is coordinator-free: barrier-directory
  ``ctlev.<rank>`` records (the membership pattern) in MP mode, an
  in-process :class:`~bluefog_tpu.control.evidence.EvidenceBoard` in
  thread mode, with wire evidence kept fresh between deposits by the
  heartbeat piggyback;
- decisions are DETERMINISTIC functions of the disseminated evidence
  with hysteresis + cooldowns (every rank converges on the same plan —
  byte-identical, property-tested — and oscillating telemetry cannot
  flap it);
- actuation happens ONLY at round boundaries (the BF-CTL001 lint
  enforces the call-site discipline), so the exact push-sum mass audit
  holds through every plan change: a plan moves edges, stretches
  cadence, or retunes the wire codec — it never creates or destroys
  mass.

The decision table, the dissemination protocol, and the actuation
contract are documented in ``docs/control.md``; the A/B chaos bench
(``benchmarks/control_bench.py`` -> ``BENCH_control.json``) shows the
controller beating the frozen config under injected slow-peer +
lossy-link scenarios.  Wire the controller into a run with the
``control=ControlConfig(...)`` argument of
:func:`~bluefog_tpu.runtime.async_windows.run_async_dsgd` /
:func:`~bluefog_tpu.runtime.async_windows.run_async_dsgd_rank`.
"""

from bluefog_tpu.control.controller import (CommController, decide_plan,
                                            plan_topology)
from bluefog_tpu.control.evidence import (Evidence, EvidenceBoard,
                                          canonicalize, clear_evidence,
                                          read_evidence, write_evidence)
from bluefog_tpu.control.plan import CODEC_LADDER, CommPlan, ControlConfig
from bluefog_tpu.control.transport import (TransportConfig, TransportPlan,
                                           decide_transport_plan)
from bluefog_tpu.control.tree import (TreeConfig, TreeEvidence, TreePlan,
                                      decide_tree_plan, tree_capacity)

__all__ = [
    "CODEC_LADDER",
    "CommController",
    "CommPlan",
    "ControlConfig",
    "Evidence",
    "EvidenceBoard",
    "TransportConfig",
    "TransportPlan",
    "TreeConfig",
    "TreeEvidence",
    "TreePlan",
    "canonicalize",
    "clear_evidence",
    "decide_plan",
    "decide_transport_plan",
    "decide_tree_plan",
    "plan_topology",
    "read_evidence",
    "tree_capacity",
    "write_evidence",
]
