"""The communication plan: the controller's round-stamped output.

A :class:`CommPlan` is everything the gossip loop actuates at a round
boundary — the per-peer degree penalties, the densify level, the
local-SGD gossip cadence, and the wire-codec aggressiveness — plus the
``round`` stamp that says *when* it takes effect and a monotone
``version`` so loops can tell "new plan" from "same plan re-derived".

Determinism is the load-bearing property: a plan is a pure function of
the disseminated evidence (see :func:`bluefog_tpu.control.controller.
decide_plan`), and :meth:`CommPlan.to_bytes` is a CANONICAL encoding
(sorted keys, tuple-normalized fields), so "every rank converges on the
same plan" is checkable as literal byte equality — which is exactly what
the plan-convergence property test asserts.

:class:`ControlConfig` is the knob bag: every threshold is an
enter/exit PAIR (hysteresis — the condition that turns a knob on is
strictly stronger than the one that turns it back off, so telemetry
oscillating around a single threshold cannot flap the plan) and plan
changes are rate-limited by ``cooldown_rounds`` (a changed plan is
immune to further change until the cooldown expires, so evidence
turbulence right after an actuation — which the actuation itself causes
— cannot trigger a second one).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional, Tuple

from bluefog_tpu.topology.graphs import MAX_DENSIFY

__all__ = ["CommPlan", "ControlConfig", "CODEC_LADDER"]

# wire-codec aggressiveness ladder: index 0 = uncompressed, rising =
# more aggressive (lossier).  The controller BACKS OFF (index down) when
# consensus distance grows — compression error is the first suspect —
# and steps back up only when consensus is contracting again.
CODEC_LADDER: Tuple[Optional[str], ...] = (None, "f32", "topk")


@dataclasses.dataclass(frozen=True)
class CommPlan:
    """One round-stamped communication plan.

    Attributes:
      version: monotone plan number; 0 is the static launch config.
        Loops compare versions to detect "a new plan arrived".
      round: the decision round — actuation happens at the first round
        BOUNDARY at or after it (never mid-round; BF-CTL001 enforces
        the call-site discipline).
      slow: sorted ranks whose edges the penalized rebuild reduces to
        the ring spine (see :func:`bluefog_tpu.topology.graphs.
        replan_penalized`).
      densify: extra-edge level 0..MAX_DENSIFY when measured mixing
        lags the spectral-gap prediction.
      gossip_every: deposit/gossip every g-th step (the local-SGD
        cadence; 1 = every step).
      codec_level: index into :data:`CODEC_LADDER` (bounded by the
        caller's configured ceiling).
    """

    version: int = 0
    round: int = 0
    slow: Tuple[int, ...] = ()
    densify: int = 0
    gossip_every: int = 1
    codec_level: int = 0

    def __post_init__(self):
        object.__setattr__(self, "slow",
                           tuple(sorted(int(r) for r in self.slow)))
        object.__setattr__(self, "densify",
                           max(0, min(int(self.densify), MAX_DENSIFY)))
        object.__setattr__(self, "gossip_every",
                           max(1, int(self.gossip_every)))
        object.__setattr__(self, "codec_level",
                           max(0, min(int(self.codec_level),
                                      len(CODEC_LADDER) - 1)))

    @property
    def codec(self) -> Optional[str]:
        """The wire-codec name this plan selects (None = uncompressed)."""
        return CODEC_LADDER[self.codec_level]

    def to_bytes(self) -> bytes:
        """Canonical byte encoding: sorted keys, normalized field types.
        Two ranks that derived the same plan produce IDENTICAL bytes —
        the convergence property the tests assert literally."""
        return json.dumps(
            {"version": int(self.version), "round": int(self.round),
             "slow": list(self.slow), "densify": int(self.densify),
             "gossip_every": int(self.gossip_every),
             "codec_level": int(self.codec_level)},
            sort_keys=True, separators=(",", ":")).encode()

    @staticmethod
    def from_bytes(blob: bytes) -> "CommPlan":
        d = json.loads(blob.decode())
        return CommPlan(version=int(d["version"]), round=int(d["round"]),
                        slow=tuple(d["slow"]), densify=int(d["densify"]),
                        gossip_every=int(d["gossip_every"]),
                        codec_level=int(d["codec_level"]))


@dataclasses.dataclass(frozen=True)
class ControlConfig:
    """Knobs for the self-tuning communication controller.

    Every decision threshold is an enter/exit pair with the enter side
    strictly stronger — hysteresis, so telemetry oscillating around one
    value cannot flap the plan — and ``cooldown_rounds`` rate-limits
    changes so one actuation's own turbulence cannot trigger the next.
    """

    # evidence cadence: publish local evidence + re-decide every K
    # gossip rounds (a multiple keeps the barrier-dir scan off the hot
    # path, same posture as the tombstone poll)
    evidence_every: int = 8
    # EWMA smoothing for the thread-mode staleness/lag signal
    ewma_alpha: float = 0.25
    # slow-peer detection: a peer enters the slow set when its observed
    # lag (wire: ack EWMA; thread: seconds since its last fresh deposit)
    # exceeds slow_enter x the fleet median, and leaves only below
    # slow_exit x the median — the hysteresis band.  min_lag_s is an
    # absolute floor: nobody is "slow" below it no matter the ratio
    # (sub-millisecond medians make ratios meaningless noise).
    slow_enter: float = 4.0
    slow_exit: float = 2.0
    min_lag_s: float = 0.01
    # a peer also enters the slow set on lossy-link evidence: at least
    # this many reconnect cycles observed against it across reporters
    # within one evidence window
    reconnects_enter: int = 2
    # densify ladder on mixing excess (measured minus predicted
    # contraction; persistently positive = gossip under-delivering)
    densify_enter: float = 0.15
    densify_exit: float = 0.02
    # size-aware ladder cap (the digital twin's scale-blindness
    # finding, PR 13): the ladder's top rung is the one-step exact
    # averager — a million-edge plan at 1024 ranks.  Fully-connected
    # stays reachable only for fleets at or below this many live
    # reporters; larger fleets top out at the symmetric-exponential
    # rung (level 1, out-degree ~2·log2 m), so the ladder can stay
    # ENABLED at fleet scale instead of being configured off
    densify_full_max: int = 64
    # gossip-cadence band on the local consensus-growth ratio
    # (disagreement now / disagreement one evidence window ago):
    # > grow_hi -> gossip MORE (halve gossip_every) and back the codec
    # off one rung; < grow_lo with slow links present -> gossip LESS
    # (double gossip_every up to cadence_max) to take pressure off the
    # slow wire
    grow_hi: float = 1.05
    grow_lo: float = 0.7
    cadence_max: int = 4
    # codec ceiling: highest CODEC_LADDER index the controller may use
    # (0 keeps compression off — the right ceiling whenever the exact
    # mass audit matters; see docs/control.md)
    max_codec_level: int = 0
    # link-vs-host split on traced phase evidence: a peer ENTERING the
    # slow set on lag whose (net, queue, apply) decomposition is
    # net-dominated (net fraction >= this) is a slow LINK — when the
    # codec ladder has headroom the plan compresses harder instead of
    # ring-spining the peer (a thin wire wants fewer bytes; a slow HOST
    # wants fewer edges).  Ignored when no reporter carried phase
    # evidence (tracing off), so pre-tracing fleets decide identically.
    link_net_frac: float = 0.6
    # plan-change rate limit (rounds)
    cooldown_rounds: int = 16
    # never penalize more than this fraction of the member set (the
    # controller must degrade links, not dissolve the fleet)
    max_slow_frac: float = 0.5

    def __post_init__(self):
        if self.evidence_every < 1:
            raise ValueError("evidence_every must be >= 1")
        if not (self.slow_exit < self.slow_enter):
            raise ValueError(
                "hysteresis requires slow_exit < slow_enter "
                f"(got exit={self.slow_exit}, enter={self.slow_enter})")
        if not (self.densify_exit < self.densify_enter):
            raise ValueError(
                "hysteresis requires densify_exit < densify_enter")
        if self.densify_full_max < 1:
            raise ValueError("densify_full_max must be >= 1")
        if not (self.grow_lo < self.grow_hi):
            raise ValueError("hysteresis requires grow_lo < grow_hi")
        if not (0 <= self.max_codec_level < len(CODEC_LADDER)):
            raise ValueError(
                f"max_codec_level must be in [0, {len(CODEC_LADDER) - 1}]")
        if self.cooldown_rounds < 1:
            raise ValueError("cooldown_rounds must be >= 1")
        if not (0.0 < self.link_net_frac <= 1.0):
            raise ValueError("link_net_frac must be in (0, 1]")
