"""TFRecord on-disk format: writer, indexed random-access reader, tf.Example
codec — no TensorFlow dependency.

The reference's examples read datasets through torch ``DataLoader`` +
``DistributedSampler`` over on-disk files (SURVEY.md §2.2 "Examples"); the
TPU ecosystem's interchange container is TFRecord.  This module implements
the container natively:

- **Framing** (`TFRecordWriter`, :func:`read_records`,
  :class:`TFRecordSource`): the standard ``uint64 length | masked crc32c |
  payload | masked crc32c`` record stream.  Checksums and the shard-indexing
  scan run in the native C++ runtime (``csrc/tfrecord.cc``) when available,
  with a pure-Python fallback.
- **tf.Example codec** (:func:`encode_example` / :func:`decode_example`): a
  minimal hand-rolled protobuf subset (Example → Features → map<string,
  Feature{bytes_list,float_list,int64_list}>) — wire-compatible with
  TensorFlow-written files that use those (ubiquitous) fields.
- **Random access**: :class:`TFRecordSource` indexes every shard once
  (offset/length tables), then serves ``source[idx_array]`` gathers through
  memory-maps — the gatherable-source contract of
  :class:`~bluefog_tpu.data.loader.DistributedLoader`, so decentralized
  rank-sharding, static batches, and prefetch all apply unchanged.
"""

from __future__ import annotations

import ctypes
import glob as _glob
import io
import os
import struct
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "crc32c",
    "TFRecordWriter",
    "read_records",
    "encode_example",
    "decode_example",
    "TFRecordSource",
    "image_classification_decoder",
    "write_image_classification_shards",
]


# ---------------------------------------------------------------- crc32c --

_POLY = 0x82F63B78
_PY_TABLE: Optional[np.ndarray] = None


def _py_table() -> np.ndarray:
    global _PY_TABLE
    if _PY_TABLE is None:
        table = np.zeros(256, np.uint32)
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ _POLY if crc & 1 else crc >> 1
            table[i] = crc
        _PY_TABLE = table
    return _PY_TABLE


def _native():
    from bluefog_tpu.runtime import native

    return native.load()


def crc32c(data: bytes) -> int:
    """CRC32C (Castagnoli) of ``data`` — native when available."""
    lib = _native()
    if lib is not None:
        # bytes passes directly as c_void_p (read-only) — no copy
        return int(lib.bf_crc32c(data if data else None, len(data)))
    table = _py_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = int(table[(crc ^ b) & 0xFF]) ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked(crc: int) -> int:
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------- framing --


class TFRecordWriter:
    """Append records to one TFRecord file (context manager)."""

    def __init__(self, path: str):
        self._f = open(path, "wb")

    def write(self, payload: bytes) -> None:
        header = struct.pack("<Q", len(payload))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked(crc32c(header))))
        self._f.write(payload)
        self._f.write(struct.pack("<I", _masked(crc32c(payload))))

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _index_file_py(path: str, verify: bool) -> Tuple[np.ndarray, np.ndarray]:
    offsets, lengths = [], []
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if not header:
                break
            if len(header) != 12:
                raise ValueError(f"{path}: truncated record header")
            (length,) = struct.unpack("<Q", header[:8])
            (len_crc,) = struct.unpack("<I", header[8:])
            if verify and _masked(crc32c(header[:8])) != len_crc:
                raise ValueError(
                    f"{path}: length checksum mismatch at record "
                    f"{len(offsets)}")
            off = f.tell()
            if off + length + 4 > size:
                raise ValueError(f"{path}: truncated record payload")
            if verify:
                payload = f.read(length)
                (data_crc,) = struct.unpack("<I", f.read(4))
                if _masked(crc32c(payload)) != data_crc:
                    raise ValueError(
                        f"{path}: payload checksum mismatch at record "
                        f"{len(offsets)}")
            else:
                f.seek(length + 4, io.SEEK_CUR)
            offsets.append(off)
            lengths.append(length)
    return np.asarray(offsets, np.int64), np.asarray(lengths, np.int64)


def _index_file(path: str, verify: bool) -> Tuple[np.ndarray, np.ndarray]:
    lib = _native()
    if lib is None:
        return _index_file_py(path, verify)
    bad = ctypes.c_longlong(-1)
    n = lib.bf_tfrecord_index(path.encode(), None, None, 0, 0, None)
    if n == -1:
        raise FileNotFoundError(path)
    if n < 0:
        raise ValueError(f"{path}: malformed TFRecord framing")
    offsets = np.zeros(n, np.int64)
    lengths = np.zeros(n, np.int64)
    rc = lib.bf_tfrecord_index(
        path.encode(),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        n, 1 if verify else 0, ctypes.byref(bad))
    if rc == -3:
        raise ValueError(f"{path}: checksum mismatch at record {bad.value}")
    if rc < 0:
        raise ValueError(f"{path}: malformed TFRecord framing")
    return offsets, lengths


def read_records(path: str, *, verify: bool = True) -> Iterable[bytes]:
    """Yield every record payload of one file (sequential read)."""
    offsets, lengths = _index_file(path, verify)
    with open(path, "rb") as f:
        for off, ln in zip(offsets, lengths):
            f.seek(int(off))
            yield f.read(int(ln))


# ----------------------------------------------------- tf.Example codec --
# Minimal protobuf wire subset.  Message graph (field numbers per the public
# tensorflow/core/example/{example,feature}.proto):
#   Example      { Features features = 1; }
#   Features     { map<string, Feature> feature = 1; }
#   Feature      { oneof: BytesList=1 | FloatList=2 | Int64List=3 }
#   BytesList    { repeated bytes value = 1; }
#   FloatList    { repeated float value = 1 [packed]; }
#   Int64List    { repeated int64 value = 1 [packed]; }


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _len_field(num: int, payload: bytes) -> bytes:
    return _varint((num << 3) | 2) + _varint(len(payload)) + payload


def encode_example(features: Dict[str, object]) -> bytes:
    """Encode a feature dict as a serialized ``tf.Example``.

    Value types: ``bytes``/list of bytes → bytes_list; float arrays →
    float_list; int arrays → int64_list.
    """
    entries = b""
    for key, value in features.items():
        if isinstance(value, bytes):
            value = [value]
        if isinstance(value, (list, tuple)) and value and isinstance(value[0], bytes):
            flist = _len_field(1, b"".join(_len_field(1, v) for v in value))
        else:
            arr = np.asarray(value)
            if arr.dtype.kind == "f":
                packed = arr.astype("<f4").tobytes()
                flist = _len_field(2, _len_field(1, packed))
            elif arr.dtype.kind in "iub":
                vals = b"".join(_varint(int(v) & 0xFFFFFFFFFFFFFFFF)
                                for v in arr.reshape(-1))
                flist = _len_field(3, _len_field(1, vals))
            else:
                raise TypeError(f"feature {key!r}: unsupported dtype {arr.dtype}")
        entry = _len_field(1, key.encode()) + _len_field(2, flist)
        entries += _len_field(1, entry)
    return _len_field(1, entries)


def _parse_fields(buf: bytes):
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        num, wire = tag >> 3, tag & 7
        if wire == 2:
            ln, pos = _read_varint(buf, pos)
            yield num, buf[pos:pos + ln]
            pos += ln
        elif wire == 0:
            val, pos = _read_varint(buf, pos)
            yield num, val
        elif wire == 5:
            yield num, buf[pos:pos + 4]
            pos += 4
        elif wire == 1:
            yield num, buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported protobuf wire type {wire}")


def decode_example(payload: bytes) -> Dict[str, object]:
    """Parse a serialized ``tf.Example`` into ``{name: np.ndarray | [bytes]}``."""
    out: Dict[str, object] = {}
    for num, features_buf in _parse_fields(payload):
        if num != 1:
            continue
        for fnum, entry in _parse_fields(features_buf):
            if fnum != 1:
                continue
            key, feature = None, None
            for enum_, v in _parse_fields(entry):
                if enum_ == 1:
                    key = v.decode()
                elif enum_ == 2:
                    feature = v
            if key is None or feature is None:
                continue
            for kind, lst in _parse_fields(feature):
                if kind == 1:  # bytes_list
                    out[key] = [v for n_, v in _parse_fields(lst) if n_ == 1]
                elif kind == 2:  # float_list (packed or repeated fixed32)
                    vals: List[bytes] = []
                    for n_, v in _parse_fields(lst):
                        if n_ == 1:
                            vals.append(v)
                    out[key] = np.frombuffer(b"".join(vals), "<f4")
                elif kind == 3:  # int64_list (packed or repeated varint)
                    ints: List[int] = []
                    for n_, v in _parse_fields(lst):
                        if n_ != 1:
                            continue
                        if isinstance(v, int):
                            ints.append(v)
                        else:
                            p = 0
                            while p < len(v):
                                val, p = _read_varint(v, p)
                                ints.append(val)
                    out[key] = np.asarray(
                        [i - (1 << 64) if i >= (1 << 63) else i for i in ints],
                        np.int64)
    return out


# ------------------------------------------------------------- the source --


def image_classification_decoder(example: Dict[str, object]
                                 ) -> Tuple[np.ndarray, np.int32]:
    """Decode ``{image: raw uint8 bytes, shape: int64[3], label: int64}``."""
    shape = tuple(np.asarray(example["shape"], np.int64))
    img = np.frombuffer(example["image"][0], np.uint8).reshape(shape)
    return img, np.int32(np.asarray(example["label"])[0])


class TFRecordSource:
    """Index-gatherable source over TFRecord shards for
    :class:`~bluefog_tpu.data.loader.DistributedLoader`.

    ``pattern`` is a glob (or explicit list of paths); shards are indexed
    once at construction (native framing scan), then records are served by
    random access through per-shard memory maps.  ``decode`` maps a parsed
    example dict to a tuple of arrays (default:
    :func:`image_classification_decoder`).
    """

    def __init__(self, pattern, *, decode: Optional[Callable] = None,
                 verify: bool = False):
        paths = (sorted(_glob.glob(pattern)) if isinstance(pattern, str)
                 else list(pattern))
        if not paths:
            raise FileNotFoundError(f"no TFRecord shards match {pattern!r}")
        self.paths = paths
        self.decode = decode or image_classification_decoder
        self._mmaps: List[Optional[np.memmap]] = [None] * len(paths)
        shard_ids, offsets, lengths = [], [], []
        for s, p in enumerate(paths):
            off, ln = _index_file(p, verify)
            shard_ids.append(np.full(len(off), s, np.int32))
            offsets.append(off)
            lengths.append(ln)
        self._shard = np.concatenate(shard_ids)
        self._off = np.concatenate(offsets)
        self._len = np.concatenate(lengths)

    def __len__(self) -> int:
        return len(self._off)

    def _mm(self, s: int) -> np.memmap:
        if self._mmaps[s] is None:
            self._mmaps[s] = np.memmap(self.paths[s], np.uint8, mode="r")
        return self._mmaps[s]

    def record(self, i: int) -> bytes:
        s = int(self._shard[i])
        off, ln = int(self._off[i]), int(self._len[i])
        return bytes(self._mm(s)[off:off + ln])

    def __getitem__(self, idx):
        idx = np.atleast_1d(np.asarray(idx))
        decoded = [self.decode(decode_example(self.record(int(i))))
                   for i in idx.reshape(-1)]
        cols = tuple(np.stack([d[c] for d in decoded])
                     for c in range(len(decoded[0])))
        return cols if len(cols) > 1 else cols[0]


def write_image_classification_shards(
    directory: str,
    images: np.ndarray,
    labels: np.ndarray,
    *,
    shard_size: int = 1024,
    prefix: str = "data",
) -> List[str]:
    """Write ``(N, H, W, C) uint8`` images + int labels as TFRecord shards
    (the generator used by tests and by ``imagenet_resnet.py`` docs)."""
    images = np.asarray(images)
    labels = np.asarray(labels)
    if images.dtype != np.uint8:
        raise TypeError(f"images must be uint8, got {images.dtype}")
    os.makedirs(directory, exist_ok=True)
    paths = []
    n_shards = (len(images) + shard_size - 1) // shard_size
    for s in range(n_shards):
        path = os.path.join(
            directory, f"{prefix}-{s:05d}-of-{n_shards:05d}.tfrecord")
        with TFRecordWriter(path) as w:
            for i in range(s * shard_size,
                           min((s + 1) * shard_size, len(images))):
                w.write(encode_example({
                    "image": images[i].tobytes(),
                    "shape": np.asarray(images[i].shape, np.int64),
                    "label": np.asarray([labels[i]], np.int64),
                }))
        paths.append(path)
    return paths
