"""Input pipeline: disjoint rank sharding, static batches, device prefetch.

The reference's examples use torch DataLoader + DistributedSampler (SURVEY.md
§2.2 "Examples"); this is the TPU-native equivalent feeding the stacked
``(num_ranks, batch, ...)`` layout the repo's shard_map train steps consume.
"""

from bluefog_tpu.data.loader import (
    Subset,
    ArraySource,
    DistributedLoader,
    SyntheticClassificationSource,
    prefetch_to_device,
)
from bluefog_tpu.data.tfrecord import (
    TFRecordSource,
    TFRecordWriter,
    decode_example,
    encode_example,
    image_classification_decoder,
    read_records,
    write_image_classification_shards,
)

__all__ = [
    "ArraySource",
    "Subset",
    "DistributedLoader",
    "SyntheticClassificationSource",
    "prefetch_to_device",
    "TFRecordSource",
    "TFRecordWriter",
    "decode_example",
    "encode_example",
    "image_classification_decoder",
    "read_records",
    "write_image_classification_shards",
]
