"""TPU-native input pipeline for decentralized data-parallel training.

The reference has no data subsystem of its own — its examples feed
``torch.utils.data.DataLoader`` + ``DistributedSampler`` (upstream
``examples/pytorch_mnist.py``; SURVEY.md §2.2 "Examples").  The TPU build
needs a native equivalent because the input path is host-side work that must
overlap device compute:

- **Disjoint rank shards** — rank ``r`` draws the permuted index stream
  ``r::num_ranks`` (the DistributedSampler contract: every example seen once
  per epoch across ranks, shards disjoint).
- **Static shapes** — batches are fixed-size (remainder dropped) so the
  jitted train step never recompiles.
- **Stacked layout** — each yield is a pytree of ``(num_ranks, batch, ...)``
  arrays placed with the gossip-axis sharding
  (:func:`bluefog_tpu.parallel.api.rank_shard`), ready for the repo's
  ``shard_map(train_step, in_specs=P(axis))`` convention.
- **Background prefetch** — a host thread gathers + ``device_put``s ahead of
  the consumer, so H2D transfer rides under the previous step's compute
  (jax device_put is async; the queue bounds look-ahead).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterator, Optional, Sequence, Tuple

import jax
import numpy as np

__all__ = [
    "ArraySource",
    "Subset",
    "SyntheticClassificationSource",
    "DistributedLoader",
    "prefetch_to_device",
]


class Subset:
    """Index-range view over a source — the train/test split of one dataset
    (used by the convergence-gate examples; a test tail held out of a
    TFRecordSource without copying it)."""

    def __init__(self, source, lo: int, hi: int):
        if not 0 <= lo <= hi <= len(source):
            raise ValueError(
                f"bad subset [{lo}, {hi}) of a {len(source)}-example source")
        self.source, self.lo = source, lo
        self.n = hi - lo

    def __len__(self):
        return self.n

    def __getitem__(self, idx):
        idx = np.asarray(idx)
        if np.any(idx < 0) or np.any(idx >= self.n):
            raise IndexError(f"index out of range for {self.n}-example subset")
        return self.source[idx + self.lo]


class ArraySource:
    """Index-gatherable source over parallel arrays (features, labels, ...).

    Accepts numpy arrays or anything ``np.asarray``-able, including
    ``np.load(..., mmap_mode="r")`` memory-maps for larger-than-RAM data.
    ``source[idx_array]`` gathers a batch from every array.
    """

    def __init__(self, *arrays):
        if not arrays:
            raise ValueError("ArraySource needs at least one array")
        self.arrays = tuple(
            a if isinstance(a, np.memmap) else np.asarray(a) for a in arrays
        )
        n = len(self.arrays[0])
        for a in self.arrays:
            if len(a) != n:
                raise ValueError(
                    f"array lengths disagree: {[len(a) for a in self.arrays]}")
        self._len = n

    def __len__(self) -> int:
        return self._len

    def __getitem__(self, idx):
        out = tuple(np.asarray(a[idx]) for a in self.arrays)
        return out if len(out) > 1 else out[0]


class SyntheticClassificationSource:
    """Procedural labeled data: ``num_classes`` fixed prototypes + noise.

    Deterministic per index (no stored dataset), so ranks can draw disjoint
    shards of an arbitrarily large virtual epoch.  Shapes default to
    ImageNet-like; pass ``shape=(28, 28, 1), num_classes=10`` for MNIST.
    """

    def __init__(self, num_examples: int, *, shape=(224, 224, 3),
                 num_classes: int = 1000, seed: int = 0, noise: float = 0.3,
                 dtype=np.float32):
        self._len = int(num_examples)
        self.shape = tuple(shape)
        self.num_classes = int(num_classes)
        self.noise = float(noise)
        self.dtype = dtype
        self._seed = seed
        # Prototypes are generated lazily per touched class — 1000 ImageNet-
        # sized f32 prototypes would be ~574 MB eager.
        self._protos: dict = {}

    def __len__(self) -> int:
        return self._len

    def _proto(self, label: int) -> np.ndarray:
        p = self._protos.get(label)
        if p is None:
            rng = np.random.default_rng((self._seed, 2, label))
            p = rng.standard_normal(self.shape).astype(self.dtype) * 0.8
            self._protos[label] = p
        return p

    def __getitem__(self, idx):
        idx = np.asarray(idx)
        labels = np.empty(idx.shape, np.int32)
        imgs = np.empty(idx.shape + self.shape, self.dtype)
        for pos, i in enumerate(idx.reshape(-1)):
            rng = np.random.default_rng((self._seed, 1, int(i)))
            lab = int(rng.integers(0, self.num_classes))
            labels.reshape(-1)[pos] = lab
            flat = imgs.reshape((-1,) + self.shape)
            flat[pos] = self._proto(lab) + self.noise * rng.standard_normal(
                self.shape).astype(self.dtype)
        return imgs, labels


def _epoch_perm(n: int, seed: int, epoch: int, shuffle: bool) -> np.ndarray:
    if not shuffle:
        return np.arange(n)
    return np.random.default_rng((seed, epoch)).permutation(n)


class DistributedLoader:
    """Epoch iterator yielding gossip-sharded stacked batches.

    Each item is a pytree (tuple) of ``(num_ranks, batch, ...)`` arrays; with
    ``device_put=True`` (default) leaves are placed with the current
    context's rank sharding and prefetched ``prefetch`` batches ahead on a
    background thread.

    Index discipline (mirrors torch ``DistributedSampler``): one global
    permutation per epoch (seeded by ``(seed, epoch)`` — identical on every
    host), rank ``r`` takes ``perm[r::num_ranks]``, remainder dropped so all
    ranks and all steps see identical static shapes.
    """

    def __init__(self, source, per_rank_batch: int, *,
                 num_ranks: Optional[int] = None, shuffle: bool = True,
                 seed: int = 0, device_put: bool = True, prefetch: int = 2):
        from bluefog_tpu.parallel.api import get_context

        self.source = source
        self.batch = int(per_rank_batch)
        if num_ranks is None:
            num_ranks = get_context().size
        elif device_put and num_ranks != get_context().size:
            raise ValueError(
                f"num_ranks={num_ranks} != context size "
                f"{get_context().size}; rank_shard placement requires them "
                "equal — pass device_put=False for host-only loading")
        self.num_ranks = int(num_ranks)
        self.shuffle = shuffle
        self.seed = int(seed)
        self.device_put = device_put
        self.prefetch = max(int(prefetch), 0)
        per_rank = len(source) // self.num_ranks
        self.steps_per_epoch = per_rank // self.batch
        if self.steps_per_epoch == 0:
            raise ValueError(
                f"source of {len(source)} examples < one batch per rank "
                f"({self.num_ranks} ranks x {self.batch})")

    def _host_batches(self, epoch: int) -> Iterator[Any]:
        perm = _epoch_perm(len(self.source), self.seed, epoch, self.shuffle)
        shards = [perm[r::self.num_ranks] for r in range(self.num_ranks)]
        for step in range(self.steps_per_epoch):
            lo = step * self.batch
            idx = np.stack([s[lo:lo + self.batch] for s in shards])  # (R, B)
            got = self.source[idx.reshape(-1)]
            if not isinstance(got, tuple):
                got = (got,)
            yield tuple(
                a.reshape((self.num_ranks, self.batch) + a.shape[1:])
                for a in got)

    def epoch(self, epoch: int = 0) -> Iterator[Any]:
        """Iterate one epoch (pass the epoch number for fresh shuffling)."""
        it = self._host_batches(epoch)
        if not self.device_put:
            return it

        from bluefog_tpu.parallel.api import rank_shard

        it = map(rank_shard, it)
        return prefetch_to_device(it, self.prefetch) if self.prefetch else it

    def __iter__(self):
        return self.epoch(0)


def prefetch_to_device(it: Iterator[Any], size: int) -> Iterator[Any]:
    """Run ``it`` on a daemon thread, keeping up to ``size`` items queued.

    Items are produced (and any ``device_put`` inside ``it`` issued) ahead of
    the consumer, overlapping host work + H2D with device compute.  Exceptions
    on the worker re-raise at the consumer's next ``next()``.
    """
    if size <= 0:
        yield from it
        return
    q: "queue.Queue" = queue.Queue(maxsize=size)
    stop = threading.Event()
    END, ERR = object(), object()

    def put(item) -> bool:
        """Bounded put that gives up when the consumer is gone."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for item in it:
                if not put(item):
                    return
            put(END)
        except BaseException as e:  # noqa: BLE001 — reraised at consumer
            put((ERR, e))

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is END:
                return
            if isinstance(item, tuple) and len(item) == 2 and item[0] is ERR:
                raise item[1]
            yield item
    finally:
        # Consumer done or abandoned early (break/exception/GeneratorExit):
        # unblock and join the worker, then drop queued batches so their
        # device buffers free promptly.
        stop.set()
        t.join(timeout=5.0)
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
