"""bluefog_tpu — a TPU-native decentralized deep-learning training framework.

A ground-up re-design of the capabilities of the reference project
``wowML/bluefog`` (a Bluefog-lineage decentralized training library for
PyTorch/MPI/NCCL) for TPUs and the JAX/XLA/Pallas stack.

Where the reference runs one OS process per rank, a C++ background engine, and
MPI/NCCL on the wire, this framework is SPMD-first:

- a *rank* is a device (or a mesh coordinate) in a ``jax.sharding.Mesh``;
- ``neighbor_allreduce`` and friends lower to ``lax.ppermute`` /
  ``lax.psum`` collectives on the ICI interconnect, fused by XLA into the
  training step (replacing the reference's background-thread + negotiation
  engine — see SURVEY.md §7);
- one-sided window ops (``win_put`` / ``win_get`` / ``win_accumulate`` /
  ``win_update``) are functional state transitions backed by ppermute on any
  backend and by Pallas async remote DMA on TPU;
- optimizers are functional wrappers compatible with optax.

Reference parity map (upstream-relative paths; the reference mount was empty
during the survey — see SURVEY.md header):

==============================================  =================================
reference                                       here
==============================================  =================================
bluefog/common/topology_util.py                 bluefog_tpu.topology
bluefog/torch/mpi_ops.py (collectives)          bluefog_tpu.ops.collectives
bluefog/torch/mpi_win_ops.{py,cc}               bluefog_tpu.ops.windows
bluefog/torch/optimizers.py                     bluefog_tpu.optim
bluefog/common/basics.py (init/rank/size/...)   bluefog_tpu.parallel.context
bluefog/common/{operations,mpi_controller}.cc   XLA SPMD + bluefog_tpu.runtime
bluefog/common/timeline.{h,cc}                  bluefog_tpu.utils.timeline
bluefog/run/ (bfrun launcher)                   bluefog_tpu.runtime.launch
==============================================  =================================
"""

from bluefog_tpu import topology
from bluefog_tpu.parallel.context import (
    init,
    shutdown,
    initialized,
    size,
    rank,
    process_rank,
    local_size,
    local_rank,
    machine_size,
    machine_rank,
    set_topology,
    load_topology,
    set_machine_topology,
    load_machine_topology,
    in_neighbor_ranks,
    out_neighbor_ranks,
    in_neighbor_machine_ranks,
    out_neighbor_machine_ranks,
    get_context,
)
from bluefog_tpu.parallel.api import (
    allreduce,
    allgather,
    broadcast,
    neighbor_allreduce,
    neighbor_allreduce_aperiodic,
    neighbor_allgather,
    hierarchical_neighbor_allreduce,
    barrier,
    win_create,
    win_free,
    win_put,
    win_get,
    win_accumulate,
    win_update,
    win_update_then_collect,
    win_mutex,
    win_mutex_break,
    win_mutex_sweep,
    broadcast_parameters,
    allreduce_parameters,
    broadcast_optimizer_state,
    rank_stack,
    rank_shard,
    enqueue_host_op,
    poll,
    synchronize,
    wait_all_host_ops,
)
from bluefog_tpu.utils import (
    timeline_start,
    timeline_stop,
    timeline_start_activity,
    timeline_end_activity,
    timeline_context,
)
from bluefog_tpu.utils.checkpoint import CheckpointManager, run_with_restart
from bluefog_tpu import metrics
from bluefog_tpu.metrics import metrics_active, metrics_start, metrics_stop
from bluefog_tpu import blackbox

__version__ = "0.1.0"
