"""The scenario regression lab: declarative specs, checkable verdicts.

A :class:`Scenario` is a TABLE ENTRY, not a script: a fleet shape, a
bounded virtual-time ``horizon_s``, a schedule of membership/fault
events (fault payloads in the one chaos spec grammar), and — the part
the BF-SIM001 lint refuses to let anyone omit — an ``accept`` tuple of
named predicates with explicit parameters.  ``bfsim-tpu --check`` runs
the whole suite and exits nonzero when any predicate fails, which makes
controller/topology changes regression-gateable at 1000 simulated ranks
the way ``BENCH_control.json`` gates the 4-rank live case.

Three scenario kinds:

- ``fleet`` — one :class:`~bluefog_tpu.sim.fleet.FleetSim` run with the
  event schedule applied;
- ``ab`` — the control-vs-static pair: the SAME seed, faults, and
  schedule run twice (``control=True`` / ``False``), compared on
  simulated time-to-target (the BENCH_control shape);
- ``mixing`` — the synchronous spectral-gap fidelity runs
  (:mod:`bluefog_tpu.sim.mixing`) over a set of topology constructors.

Alert semantics, stated plainly: scenario predicates are the gate here
— the replayed :class:`~bluefog_tpu.fleet.SLOEngine` transitions are
EVIDENCE a predicate inspects (``warn_fired`` asserts detection
happened and names the right rank), not an automatic failure the way
``bffleet-tpu --check`` treats them on a production run, because these
scenarios inject the very faults the alerts exist to catch.  A
gracefully departed rank's last record also keeps aging in the view, so
the ``silent`` SLO fires on leavers by construction — detection working
as built, asserted where a scenario expects it.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Dict, List, Mapping, Optional, Sequence, Tuple

from bluefog_tpu.control.plan import ControlConfig
from bluefog_tpu.fleet.slo import WARN, default_specs
from bluefog_tpu.sim.fleet import FleetSim, SimConfig
from bluefog_tpu.sim.mixing import run_sync_mixing
from bluefog_tpu.sim.network import LinkModel
from bluefog_tpu.sim.readers import ReaderTreeConfig, run_reader_tree
from bluefog_tpu.topology.graphs import (ExponentialTwoGraph,
                                         FullyConnectedGraph, RingGraph)

__all__ = ["Scenario", "build_suite", "run_scenario", "run_suite",
           "PREDICATES", "SCENARIO_NAMES"]

_KINDS = ("fleet", "ab", "mixing", "reader_tree")

#: the chaos-grammar spelling of a server-delayed slow host (the
#: BENCH_control fault, scaled up)
_SLOW_HOST_SPEC = "server:delay:ms=150:rate=1.0"


@dataclasses.dataclass(frozen=True)
class Scenario:
    """One table entry.  ``horizon_s`` (a bounded virtual-time horizon)
    and ``accept`` (non-empty ``(predicate name, params)`` tuple) are
    MANDATORY — enforced here at construction and by the BF-SIM001
    lint at every call site.  ``events`` is ``(t, action, params)``
    with actions ``join`` / ``leave`` / ``kill`` (``rank`` or
    ``ranks``), ``partition`` (``side_a`` / ``side_b`` rank lists),
    ``merge``, ``slow_host`` (``rank``, optional ``spec``), and
    ``compute_scale`` (``rank``, ``mult``)."""

    name: str
    kind: str
    n_ranks: int
    horizon_s: float
    accept: Tuple[Tuple[str, Mapping], ...]
    seed: int = 0
    config: Mapping = dataclasses.field(default_factory=dict)
    events: Tuple[Tuple[float, str, Mapping], ...] = ()
    topologies: Tuple[str, ...] = ()   # mixing kind only
    notes: str = ""

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"scenario {self.name!r}: unknown kind "
                             f"{self.kind!r} (want one of {_KINDS})")
        if not (isinstance(self.horizon_s, (int, float))
                and self.horizon_s > 0):
            raise ValueError(
                f"scenario {self.name!r}: horizon_s must be a positive "
                "virtual-time bound (an unbounded scenario is not a "
                "regression gate)")
        if not self.accept:
            raise ValueError(
                f"scenario {self.name!r}: accept must name at least one "
                "predicate (a scenario without an acceptance predicate "
                "is a demo, not a check)")
        for entry in self.accept:
            pname = entry[0]
            if pname not in PREDICATES:
                raise ValueError(
                    f"scenario {self.name!r}: unknown predicate "
                    f"{pname!r}; known: {sorted(PREDICATES)}")


# ---------------------------------------------------------------------------
# Predicates: (ctx, **params) -> (ok, info-dict).  ctx carries whatever
# the scenario kind produced (see run_scenario).
# ---------------------------------------------------------------------------


def _pred_audit_exact(ctx, *, tol: float = 1e-9):
    sims = ctx.get("sims") or [ctx["sim"]]
    worst = max(max(abs(e) for e in s.audit()) for s in sims)
    bound = tol * max(s.admissions for s in sims)
    return worst <= bound, {"worst_err": worst, "bound": bound}


def _pred_converged(ctx, *, eps: float, metric: str = "median"):
    sim = ctx["sim"]
    t = sim.time_to_target(eps, metric=metric)
    return t is not None, {"eps": eps, "metric": metric,
                           "time_to_target_s": t}


def _pred_plans_converged(ctx):
    sims = ctx.get("sims") or [ctx["sim"]]
    ok = all(s.plan_divergences == 0 and s.plans_converged()
             for s in sims)
    return ok, {"divergence_epochs": sum(s.plan_divergences
                                         for s in sims)}


def _pred_connected(ctx):
    sims = ctx.get("sims") or [ctx["sim"]]
    return all(s.connectivity_ok for s in sims), {}


def _pred_name_collapsed(ctx, *, max_len: int = 160):
    sims = ctx.get("sims") or [ctx["sim"]]
    worst = max(s.max_name_len for s in sims)
    return worst <= max_len, {"max_name_len": worst}


def _pred_members(ctx, *, count: int):
    sim = ctx["sim"]
    return len(sim.members()) == count, {"members": len(sim.members())}


def _pred_warn_fired(ctx, *, slo: str, rank: Optional[int] = None):
    """The detection check: the replayed SLO engine raised WARN-or-worse
    for ``slo`` (attributing ``rank`` when given)."""
    hits = []
    for tr in ctx["engine"].transitions:
        if tr.slo != slo or tr.to < WARN:
            continue
        hits.append({"round": tr.round, "rank": tr.rank,
                     "state": tr.to})
    ok = any(h for h in hits
             if rank is None or h["rank"] == rank)
    return ok, {"transitions": hits[:8], "want_rank": rank}


def _pred_plan_penalizes(ctx, *, ranks: Sequence[int],
                         min_count: int = 1):
    sim = ctx.get("sim") or ctx["control_sim"]
    hit = sorted(set(sim.plan.slow) & {int(r) for r in ranks})
    return len(hit) >= min_count, {"slow": list(sim.plan.slow),
                                   "matched": hit}


def _pred_control_beats_static(ctx, *, max_ratio: float,
                               target_rounds: Optional[int] = None,
                               eps: Optional[float] = None,
                               metric: str = "median",
                               quantile: float = 0.75):
    """Simulated time-to-target, control / static, must be at or below
    ``max_ratio``.  ``target_rounds`` clocks STEP THROUGHPUT (the
    median rank completing K rounds — each round is a local optimizer
    step in the DSGD model, the live bench's loss-target proxy);
    ``eps`` clocks consensus spread instead.  When the static run never
    reached the target inside the horizon, its time is floored at the
    horizon — the reported ratio is then an upper bound and the check
    is conservative."""
    ctl, sta = ctx["control_sim"], ctx["static_sim"]
    if target_rounds is not None:
        a = ctl.time_to_rounds(int(target_rounds), quantile=quantile)
        b = sta.time_to_rounds(int(target_rounds), quantile=quantile)
    else:
        if eps is None:
            return False, {"error": "need target_rounds or eps"}
        a = ctl.time_to_target(eps, metric=metric)
        b = sta.time_to_target(eps, metric=metric)
    horizon = ctx["horizon_s"]
    b_floor = horizon if b is None else b
    if a is None or b_floor <= 0:
        return False, {"control_ttt_s": a, "static_ttt_s": b,
                       "max_ratio": max_ratio}
    return a / b_floor <= max_ratio, {
        "control_ttt_s": a, "static_ttt_s": b,
        "static_floored_at_horizon": b is None,
        "ratio": a / b_floor, "max_ratio": max_ratio}


def _pred_relay_clean(ctx):
    """The read tree's delivery-cleanliness gate: zero torn deliveries
    consumed, zero duplicates, zero cursor regressions — across every
    relay and reader, through every scheduled kill."""
    rep = ctx["reader_tree"]
    ok = (rep["torn"] == 0 and rep["duplicates"] == 0
          and rep["regressions"] == 0)
    return ok, {"torn": rep["torn"], "duplicates": rep["duplicates"],
                "regressions": rep["regressions"],
                "deliveries": rep["deliveries"]}


def _pred_relay_staleness_bounded(ctx, *, rounds_per_tier: float):
    """Staleness adds per tier: tier t's worst observed staleness must
    stay within ``t * rounds_per_tier`` rounds of the publisher."""
    rep = ctx["reader_tree"]
    bad = {}
    for tier_s, worst in rep["worst_staleness_by_tier"].items():
        tier = int(tier_s)
        if worst > rounds_per_tier * max(1, tier):
            bad[tier_s] = worst
    return not bad, {"rounds_per_tier": rounds_per_tier,
                     "worst_by_tier": rep["worst_staleness_by_tier"],
                     "over_budget": bad}


def _pred_relay_served(ctx, *, min_final_frac: float = 0.9):
    """Every reader was served, and every reader's final round reached
    at least ``min_final_frac`` of the published rounds — kills and
    re-parents included, nobody is left behind."""
    rep = ctx["reader_tree"]
    rounds = ctx["reader_tree_rounds"]
    floor_ = min_final_frac * (rounds - 1)
    ok = (rep["readers_served"] == rep["readers"]
          and rep["min_reader_final_round"] >= floor_)
    return ok, {"readers": rep["readers"],
                "readers_served": rep["readers_served"],
                "min_final_round": rep["min_reader_final_round"],
                "required_floor": floor_}


def _pred_mixing_match(ctx, *, tol: float):
    """Every non-degenerate topology's geometric-mean contraction is
    within ``tol`` of its |lambda_2| prediction; one-step averagers are
    checked on the float-floor final distance instead."""
    rows = ctx["mixing_runs"]
    bad = []
    for row in rows:
        if math.isnan(row["measured"]):
            if not row["final_distance"] <= 1e-12:
                bad.append(row["topology"])
        elif abs(row["measured"] - row["predicted"]) > tol:
            bad.append(row["topology"])
    return not bad, {"tol": tol, "failed": bad}


PREDICATES: Dict[str, Callable] = {
    "audit_exact": _pred_audit_exact,
    "converged": _pred_converged,
    "plans_converged": _pred_plans_converged,
    "connected": _pred_connected,
    "name_collapsed": _pred_name_collapsed,
    "members": _pred_members,
    "warn_fired": _pred_warn_fired,
    "plan_penalizes": _pred_plan_penalizes,
    "control_beats_static": _pred_control_beats_static,
    "mixing_match": _pred_mixing_match,
    "relay_clean": _pred_relay_clean,
    "relay_staleness_bounded": _pred_relay_staleness_bounded,
    "relay_served": _pred_relay_served,
}


# ---------------------------------------------------------------------------
# The suite
# ---------------------------------------------------------------------------


def _spread(n: int, count: int, *, exclude=()) -> List[int]:
    """``count`` ranks spread deterministically over ``range(n)``."""
    step = max(1, n // max(1, count))
    out: List[int] = []
    r = step // 2
    banned = set(int(x) for x in exclude)
    while len(out) < count:
        if r % n not in banned and r % n not in out:
            out.append(r % n)
        r += step
        if len(out) < count and r > 4 * n * step:
            break
    return sorted(out[:count])


def diurnal_autoscale(n: int = 1024, seed: int = 0) -> Scenario:
    """Capacity ``n``; three-quarters run steady, the last quarter joins
    at the virtual morning, drains at the virtual evening, and joins
    again — two membership swings through the real replan, audited
    exactly, with the provenance name required to stay collapsed."""
    grow = list(range(3 * n // 4, n))
    events: List[Tuple[float, str, Mapping]] = []
    events.append((0.8, "join", {"ranks": grow}))
    events.append((1.8, "leave", {"ranks": grow}))
    events.append((2.8, "join", {"ranks": grow}))
    return Scenario(
        name="diurnal_autoscale", kind="fleet", n_ranks=n, seed=seed,
        horizon_s=4.5,
        config={"capacity": n,
                "initial_members": list(range(3 * n // 4)),
                "fleet_every": 8},
        events=tuple(events),
        accept=(
            ("audit_exact", {"tol": 1e-9}),
            ("connected", {}),
            ("plans_converged", {}),
            ("name_collapsed", {"max_len": 160}),
            ("members", {"count": n}),
            ("converged", {"eps": 1e-6, "metric": "max"}),
        ),
        notes="two grow/shrink swings; graceful drains conserve mass")


def network_partition(n: int = 1024, seed: int = 0) -> Scenario:
    """The fleet splits into halves for 1.5 virtual seconds: gossip
    links across the cut fail (evidence still rides the shared barrier
    dir, as live), controllers converge on a plan that spines the
    unreachable peers, the straggler SLO fires, and after the merge the
    fleet reconverges with the audit exact throughout."""
    side_a = list(range(n // 2))
    side_b = list(range(n // 2, n))
    return Scenario(
        name="network_partition", kind="fleet", n_ranks=n, seed=seed,
        horizon_s=7.0,
        # the densify ladder is ENABLED here — the size-aware cap
        # (ControlConfig.densify_full_max) is what made that possible:
        # a partition's stall is a genuine sustained mixing excess, and
        # above the cap decide_plan tops the ladder out at the
        # symmetric-exponential rung (~2·log2 m out-degree) instead of
        # the one-step exact averager's million-edge plan at 1024
        # ranks.  Small trims (m <= densify_full_max) still reach the
        # FC rung, matching tests/test_sim.py's small-scale ladder
        # climb.
        config={"control": True, "fleet_every": 8,
                "control_cfg": {"cooldown_rounds": 8}},
        events=(
            (1.0, "partition", {"side_a": side_a, "side_b": side_b}),
            (2.5, "merge", {}),
        ),
        accept=(
            ("audit_exact", {"tol": 1e-9}),
            ("warn_fired", {"slo": "straggler"}),
            ("plans_converged", {}),
            ("connected", {}),
            ("converged", {"eps": 1e-5, "metric": "max"}),
        ),
        notes="halves cut 1.5s; reconverges after merge")


def flash_crowd(n: int = 1024, seed: int = 0) -> Scenario:
    """Half the capacity is running; the other half joins in ONE
    admission wave (the flash crowd): one replan boundary, warm-started
    joiners, exact audit over the doubled fleet."""
    joiners = list(range(n // 2, n))
    return Scenario(
        name="flash_crowd", kind="fleet", n_ranks=n, seed=seed,
        horizon_s=3.0,
        config={"capacity": n,
                "initial_members": list(range(n // 2)),
                "control": True, "fleet_every": 8,
                "control_cfg": {"cooldown_rounds": 8}},
        events=((1.0, "join", {"ranks": joiners}),),
        accept=(
            ("audit_exact", {"tol": 1e-9}),
            ("members", {"count": n}),
            ("connected", {}),
            ("plans_converged", {}),
            ("converged", {"eps": 1e-6, "metric": "max"}),
        ),
        notes="n/2 ranks admitted in one wave")


def cascading_slow_peers(n: int = 1024, seed: int = 0) -> Scenario:
    """Slow hosts appear in waves (server-delayed, the BENCH_control
    fault) until ~15% of the fleet is slow — enough that MOST ranks
    fence on some slow out-neighbor (at out-degree ~log2 n that takes
    a double-digit slow fraction).  Run twice from the same seed: the
    controller must penalize the slow set and beat the static config on
    simulated time-to-target (the BENCH_control ratio, directionally).
    The waves start within the fleet's first contraction decades —
    a fault injected after convergence gates nothing."""
    n_slow = max(2, n * 15 // 100)
    slow = _spread(n, n_slow)
    waves = 4
    per = max(1, len(slow) // waves)
    events: List[Tuple[float, str, Mapping]] = []
    for w in range(waves):
        chunk = slow[w * per:(w + 1) * per] if w < waves - 1 \
            else slow[(waves - 1) * per:]
        if chunk:
            events.append(
                (0.12 + 0.3 * w, "slow_host", {"ranks": chunk}))
    return Scenario(
        name="cascading_slow_peers", kind="ab", n_ranks=n, seed=seed,
        horizon_s=14.0,
        config={"fleet_every": 8,
                "control_cfg": {"cooldown_rounds": 8}},
        events=tuple(events),
        accept=(
            ("audit_exact", {"tol": 1e-9}),
            ("control_beats_static",
             {"target_rounds": 72, "max_ratio": 0.6}),
            ("plan_penalizes", {"ranks": slow,
                                "min_count": max(1, len(slow) // 2)}),
            ("warn_fired", {"slo": "straggler"}),
            ("converged", {"eps": 1e-5, "metric": "median"}),
        ),
        notes=f"{len(slow)} hosts turn slow in {waves} waves; "
              "control vs static A/B")


def reader_tree(n: int = 1024, seed: int = 0) -> Scenario:
    """The read path at planet-ish scale: a depth-2, degree-16 relay
    tree fanning one publisher out to ~2n readers (thousands at the
    acceptance scale; capacity 16^3 = 4096 holds them at honest
    per-node degree), with a mid-tree relay killed while rounds roll.
    Accepts only if every delivery chain stayed clean (zero torn/
    duplicate/regressed deliveries), per-tier staleness stayed within
    its additive budget, and every reader — including the dead relay's
    re-parented children — reached the end of the run."""
    readers = max(64, 2 * n)
    rounds = 120
    return Scenario(
        name="reader_tree", kind="reader_tree", n_ranks=n, seed=seed,
        horizon_s=rounds * 0.01 + 2.0,
        # hops run at a meaningful fraction of the publish cadence, so
        # the per-tier staleness budget is genuinely exercised (worst
        # observed staleness is nonzero and must still fit the additive
        # bound), not vacuously zero
        config={"readers": readers, "degree": 16, "depth": 2,
                "rounds": rounds, "publish_dt": 0.01, "hop_dt": 0.009,
                "reparent_dt": 0.05},
        events=((0.5, "kill", {"tier": 1, "index": 0}),),
        accept=(
            ("relay_clean", {}),
            ("relay_staleness_bounded", {"rounds_per_tier": 3.0}),
            ("relay_served", {"min_final_frac": 0.9}),
        ),
        notes=f"{readers} readers behind a depth-2 tree; one tier-1 "
              "relay killed mid-run")


def mixing_fidelity(n: int = 1024, seed: int = 0) -> Scenario:
    """The headline physics check: simulated synchronous gossip on a
    1-D consensus state must contract at the |lambda_2| the real
    MixingTracker predicts — ring, exponential-2, and the one-step
    fully connected averager, at the full rank count."""
    return Scenario(
        name="mixing_fidelity", kind="mixing", n_ranks=n, seed=seed,
        horizon_s=3.0,   # rounds = horizon_s / base_round_s nominal
        topologies=("ring", "exp2", "fc"),
        accept=(("mixing_match", {"tol": 0.02}),),
        notes="measured geometric contraction vs spectral-gap "
              "prediction")


SCENARIO_NAMES: Tuple[str, ...] = (
    "mixing_fidelity",
    "diurnal_autoscale",
    "network_partition",
    "flash_crowd",
    "cascading_slow_peers",
    "reader_tree",
)

_FACTORIES = {
    "diurnal_autoscale": diurnal_autoscale,
    "network_partition": network_partition,
    "flash_crowd": flash_crowd,
    "cascading_slow_peers": cascading_slow_peers,
    "mixing_fidelity": mixing_fidelity,
    "reader_tree": reader_tree,
}


def build_suite(n: int = 1024, seed: int = 0,
                names: Optional[Sequence[str]] = None
                ) -> Tuple[Scenario, ...]:
    """The suite at rank count ``n`` (>= 1024 is the acceptance scale;
    small ``n`` is the tier-1 smoke trim — same scenarios, same
    predicates, scaled schedules)."""
    picked = tuple(names) if names else SCENARIO_NAMES
    unknown = [x for x in picked if x not in _FACTORIES]
    if unknown:
        raise ValueError(f"unknown scenario(s) {unknown}; known: "
                         f"{sorted(_FACTORIES)}")
    return tuple(_FACTORIES[x](n=n, seed=seed) for x in picked)


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------

_BASE_ROUND_S = 0.01


def _make_sim(sc: Scenario, *, control: Optional[bool] = None) -> FleetSim:
    cfgd = dict(sc.config)
    ccfg = cfgd.pop("control_cfg", None)
    if isinstance(ccfg, Mapping):
        ccfg = ControlConfig(**ccfg)
    if control is not None:
        cfgd["control"] = control
    cfg = SimConfig(n_ranks=sc.n_ranks, seed=sc.seed,
                    control_cfg=ccfg, **cfgd)
    sim = FleetSim(cfg)
    for (t, action, params) in sc.events:
        _schedule_event(sim, t, action, dict(params))
    return sim


def _ranks_of(params: Mapping) -> List[int]:
    if "ranks" in params:
        return [int(r) for r in params["ranks"]]
    return [int(params["rank"])]


def _schedule_event(sim: FleetSim, t: float, action: str,
                    params: Dict) -> None:
    if action == "join":
        ranks = _ranks_of(params)
        sim.loop.at(t, (lambda rs: lambda: [sim.join(r) for r in rs])(
            ranks))
    elif action == "leave":
        ranks = _ranks_of(params)
        sim.loop.at(
            t, (lambda rs: lambda: [sim.request_leave(r)
                                    for r in rs])(ranks))
    elif action == "kill":
        ranks = _ranks_of(params)
        sim.loop.at(t, (lambda rs: lambda: [sim.kill(r) for r in rs])(
            ranks))
    elif action == "partition":
        cut = LinkModel.cut_between(params["side_a"], params["side_b"])
        sim.loop.at(t, lambda: sim.set_partition(cut))
    elif action == "merge":
        sim.loop.at(t, lambda: sim.set_partition(None))
    elif action == "slow_host":
        spec = params.get("spec", _SLOW_HOST_SPEC)
        ranks = _ranks_of(params)
        sim.loop.at(
            t, (lambda rs, sp: lambda: [sim.set_host_faults(r, sp)
                                        for r in rs])(ranks, spec))
    elif action == "compute_scale":
        sim.loop.at(
            t, (lambda r, m: lambda: sim.set_compute_scale(r, m))(
                int(params["rank"]), float(params["mult"])))
    else:
        raise ValueError(f"unknown scenario event action {action!r}")


def _fleet_ctx(sc: Scenario) -> Dict:
    sim = _make_sim(sc)
    sim.run(sc.horizon_s)
    engine = sim.replay_slos(default_specs())
    return {"sim": sim, "sims": [sim], "engine": engine}


def _ab_ctx(sc: Scenario) -> Dict:
    # an ab run may stop SHORT of horizon_s, but only once EVERY
    # time/convergence goal the scenario's predicates will evaluate is
    # already met — the A/B target-rounds/eps plus every converged
    # predicate's eps.  (Event predicates like warn_fired must expect
    # their event before these targets; documented in docs/sim.md.)
    ab = [dict(p) for name, p in sc.accept
          if name == "control_beats_static"]
    rounds_goal = max((int(p["target_rounds"]) for p in ab
                       if p.get("target_rounds")), default=None)
    # the early stop must clock the STRICTEST quantile any predicate
    # declares, or a q=0.9 predicate could evaluate a run the q=0.75
    # default already stopped
    rounds_q = max((float(p.get("quantile", 0.75)) for p in ab
                    if p.get("target_rounds")), default=0.75)
    eps_goals = [(p["eps"], p.get("metric", "median")) for p in ab
                 if p.get("eps")]
    eps_goals += [(p["eps"], p.get("metric", "median"))
                  for name, p in sc.accept
                  if name == "converged" and p.get("eps")]
    out: Dict[str, FleetSim] = {}
    for label, control in (("static", False), ("control", True)):
        sim = _make_sim(sc, control=control)
        if rounds_goal is None and not eps_goals:
            # no time/convergence goal to clock: the horizon is the
            # run (an empty goal set must not read as "already done")
            sim.run(sc.horizon_s)
            out[label] = sim
            continue
        # run in slices so a run that already reached every goal stops
        # burning host time on the converged tail
        slice_s = max(sc.horizon_s / 28.0, 0.25)
        t = 0.0
        while t < sc.horizon_s:
            t = min(sc.horizon_s, t + slice_s)
            sim.run(t)
            done = True
            if rounds_goal is not None and \
                    sim.time_to_rounds(rounds_goal,
                                       quantile=rounds_q) is None:
                done = False
            for eps, metric in eps_goals:
                if sim.time_to_target(eps, metric=metric) is None:
                    done = False
            if done:
                break
        out[label] = sim
    engine = out["control"].replay_slos(default_specs())
    return {"sim": out["control"], "control_sim": out["control"],
            "static_sim": out["static"],
            "sims": [out["static"], out["control"]], "engine": engine}


_MIX_TOPOLOGIES = {
    "ring": RingGraph,
    "exp2": ExponentialTwoGraph,
    "fc": FullyConnectedGraph,
}


def _reader_tree_ctx(sc: Scenario) -> Dict:
    cfg = dict(sc.config)
    kills = tuple((float(t), int(p["tier"]), int(p.get("index", 0)))
                  for (t, action, p) in sc.events if action == "kill")
    rt = ReaderTreeConfig(
        readers=int(cfg.get("readers", 2048)),
        degree=int(cfg.get("degree", 8)),
        depth=int(cfg.get("depth", 2)),
        rounds=int(cfg.get("rounds", 120)),
        publish_dt_s=float(cfg.get("publish_dt", 0.01)),
        hop_dt_s=float(cfg.get("hop_dt", 0.002)),
        reparent_dt_s=float(cfg.get("reparent_dt", 0.05)),
        seed=sc.seed, kill=kills)
    rep = run_reader_tree(rt)
    return {"reader_tree": rep.as_dict(),
            "reader_tree_rounds": rt.rounds}


def _mixing_ctx(sc: Scenario) -> Dict:
    rounds = max(50, int(sc.horizon_s / _BASE_ROUND_S))
    rows = []
    for key in sc.topologies:
        topo = _MIX_TOPOLOGIES[key](sc.n_ranks)
        run = run_sync_mixing(topo, rounds=rounds, seed=sc.seed)
        rows.append({"topology": key, "n": run.n,
                     "predicted": run.predicted,
                     "measured": run.measured_geomean,
                     "rounds_used": run.rounds_used,
                     "final_distance": run.final_distance})
    return {"mixing_runs": rows}


def run_scenario(sc: Scenario) -> Dict:
    """Run one scenario and evaluate its predicates; returns the
    deterministic report dict (no wall clock anywhere in it — same
    seed, same bytes)."""
    if sc.kind == "fleet":
        ctx = _fleet_ctx(sc)
    elif sc.kind == "ab":
        ctx = _ab_ctx(sc)
    elif sc.kind == "reader_tree":
        ctx = _reader_tree_ctx(sc)
    else:
        ctx = _mixing_ctx(sc)
    ctx["horizon_s"] = sc.horizon_s

    preds: Dict[str, Dict] = {}
    ok = True
    for entry in sc.accept:
        pname, params = entry[0], dict(entry[1])
        p_ok, info = PREDICATES[pname](ctx, **params)
        key = pname if pname not in preds else \
            f"{pname}#{sum(1 for k in preds if k.startswith(pname))}"
        preds[key] = {"ok": bool(p_ok), **_jsonable(info)}
        ok = ok and bool(p_ok)

    report: Dict = {
        "name": sc.name, "kind": sc.kind, "n_ranks": sc.n_ranks,
        "seed": sc.seed, "horizon_s": sc.horizon_s,
        "predicates": preds, "ok": ok, "notes": sc.notes,
    }
    if "sim" in ctx:
        report["stats"] = _sim_stats(ctx["sim"])
        if "static_sim" in ctx:
            report["static_stats"] = _sim_stats(ctx["static_sim"])
        report["slo_transitions"] = [
            tr.describe() for tr in ctx["engine"].transitions][:24]
    if "mixing_runs" in ctx:
        report["mixing_runs"] = [_jsonable(r) for r in ctx["mixing_runs"]]
    if "reader_tree" in ctx:
        report["reader_tree"] = _jsonable(ctx["reader_tree"])
    return report


def _sim_stats(sim: FleetSim) -> Dict:
    live = sim.members()
    xerr, perr = sim.audit()
    return _jsonable({
        "virtual_end_s": sim.loop.now,
        "events": sim.loop.processed,
        "members": len(live),
        "rounds_min": min((sim.round_no[r] for r in live), default=0),
        "rounds_max": max((sim.round_no[r] for r in live), default=0),
        "admissions": sim.admissions, "leaves": sim.leaves,
        "deaths": sim.deaths,
        "audit_x_err": xerr, "audit_p_err": perr,
        "plan_version": sim.plan.version,
        "plan_slow": list(sim.plan.slow),
        "plan_changes": sim.plan_changes,
        "plan_divergences": sim.plan_divergences,
        "topology": sim.topo.name,
        "mixing_excess": sim._mixing_excess,
        "spread_final_median": (sim.spread_history[-1][1]
                                if sim.spread_history else None),
        "spread_final_max": (sim.spread_history[-1][2]
                             if sim.spread_history else None),
    })


def _jsonable(obj):
    """NaN/inf -> None, numpy scalars -> python, recursively — the
    canonical-JSON discipline so reports dump identically everywhere."""
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, bool) or obj is None or isinstance(obj, (int, str)):
        return obj
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if hasattr(obj, "item"):
        return _jsonable(obj.item())
    return str(obj)


def run_suite(n: int = 1024, seed: int = 0,
              names: Optional[Sequence[str]] = None) -> Dict:
    """Run the (possibly filtered) suite; returns the top-level report
    with the BENCH-gate ``ok`` booleans the committed ``BENCH_sim.json``
    carries."""
    reports = [run_scenario(sc) for sc in build_suite(n=n, seed=seed,
                                                      names=names)]
    return {
        "bench": "sim_scenarios",
        "n_ranks": n,
        "seed": seed,
        "scenarios": reports,
        "scenarios_ok": {r["name"] + "_ok": bool(r["ok"])
                         for r in reports},
        "ok": all(r["ok"] for r in reports),
    }
