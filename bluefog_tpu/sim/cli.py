"""``bfsim-tpu``: run the fleet digital twin's scenario lab.

::

    bfsim-tpu --list                         # scenario table
    bfsim-tpu network_partition [--ranks N]  # one scenario, full report
    bfsim-tpu --check [--ranks N] [--seed S] [--report PATH]

``--check`` runs the WHOLE suite and exits nonzero on any failed
acceptance predicate — the controller-change regression gate the
4-rank live bench cannot be.  ``--report`` writes the deterministic
JSON report (same seed, byte-identical bytes — no wall clock in it);
``BENCH_sim.json`` is exactly that file at the 1024-rank acceptance
scale, and it carries the ``*_ok`` booleans the ``bffleet-tpu --check``
bench gate verifies.

Exit codes (the CI contract, see docs/sim.md):

====  ====================================================
0     every acceptance predicate passed
2     usage error / unknown scenario
3     at least one acceptance predicate failed
====  ====================================================
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from bluefog_tpu.sim.scenarios import (SCENARIO_NAMES, build_suite,
                                       run_suite)

__all__ = ["main"]


def _print_report(doc: dict, *, verbose: bool, out) -> None:
    for rep in doc["scenarios"]:
        flag = "ok " if rep["ok"] else "FAIL"
        print(f"[{flag}] {rep['name']:22s} kind={rep['kind']:6s} "
              f"n={rep['n_ranks']}", file=out)
        for pname, info in rep["predicates"].items():
            pf = "ok " if info["ok"] else "FAIL"
            detail = {k: v for k, v in info.items() if k != "ok"}
            print(f"    [{pf}] {pname}: "
                  f"{json.dumps(detail, sort_keys=True, default=str)}",
                  file=out)
        if verbose and "stats" in rep:
            print("    stats: " + json.dumps(rep["stats"],
                                             sort_keys=True), file=out)
        if verbose:
            for line in rep.get("slo_transitions", [])[:8]:
                print("    slo: " + line, file=out)
    print(("suite: OK" if doc["ok"] else "suite: FAILED"), file=out)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bfsim-tpu",
        description="Discrete-event fleet simulator: run a scenario, or "
                    "the whole regression suite with --check (exit 0 "
                    "all predicates pass, 3 on any failure, 2 usage).")
    ap.add_argument("scenario", nargs="?", default=None,
                    help="scenario name (see --list); omit with --check")
    ap.add_argument("--check", action="store_true",
                    help="run the full scenario suite as a regression "
                    "gate")
    ap.add_argument("--ranks", type=int, default=1024,
                    help="simulated rank count (default 1024, the "
                    "acceptance scale; use a small value for a smoke "
                    "trim)")
    ap.add_argument("--seed", type=int, default=0,
                    help="scenario seed (same seed -> byte-identical "
                    "report)")
    ap.add_argument("--report", metavar="PATH", default=None,
                    help="write the deterministic JSON report here")
    ap.add_argument("--list", action="store_true",
                    help="list the scenario table and exit")
    ap.add_argument("--verbose", action="store_true",
                    help="also print per-scenario stats and SLO lines")
    args = ap.parse_args(argv)

    if args.list:
        for sc in build_suite(n=args.ranks, seed=args.seed):
            print(f"{sc.name:22s} kind={sc.kind:6s} n={sc.n_ranks:5d} "
                  f"horizon={sc.horizon_s:g}s "
                  f"predicates={[p[0] for p in sc.accept]}")
        return 0

    if args.ranks < 8:
        print("bfsim-tpu: --ranks must be >= 8", file=sys.stderr)
        return 2
    if not args.check and not args.scenario:
        print("bfsim-tpu: name a scenario or pass --check "
              f"(known: {list(SCENARIO_NAMES)})", file=sys.stderr)
        return 2
    names = None
    if args.scenario:
        if args.scenario not in SCENARIO_NAMES:
            print(f"bfsim-tpu: unknown scenario {args.scenario!r} "
                  f"(known: {list(SCENARIO_NAMES)})", file=sys.stderr)
            return 2
        names = [args.scenario]

    doc = run_suite(n=args.ranks, seed=args.seed, names=names)
    _print_report(doc, verbose=args.verbose, out=sys.stdout)
    if args.report:
        with open(args.report, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"report written to {args.report}")
    return 0 if doc["ok"] else 3


if __name__ == "__main__":
    sys.exit(main())
