import sys

from bluefog_tpu.sim.cli import main

if __name__ == "__main__":
    sys.exit(main())
