"""The fleet digital twin: an event-driven push-sum fleet at 1000 ranks.

Everything DECISION-SHAPED is the real package code; only the physics
(clock, sockets, signals) is simulated:

- the mixing graph comes from the real :func:`bluefog_tpu.topology.
  replan` / :func:`~bluefog_tpu.topology.replan_penalized` /
  :func:`~bluefog_tpu.topology.heal` at every membership and plan
  boundary — provenance-name collapse, inactive-row discipline and all;
- every rank owns a real :class:`bluefog_tpu.control.CommController`;
  per-peer lag/state/reconnect observations feed it exactly as the live
  loops do, its :class:`~bluefog_tpu.control.Evidence` records are the
  canonical-JSON objects, and plan decisions go through the real
  :func:`~bluefog_tpu.control.decide_plan` — byte-convergence across
  ranks is ASSERTED at every decision epoch;
- mixing health is a real :class:`bluefog_tpu.metrics.health.
  MixingTracker` (measured contraction on the simulated 1-D consensus
  state vs the |lambda_2| prediction), rebased at every boundary;
- fleet telemetry is real :class:`bluefog_tpu.fleet.FleetRecord`
  objects fed to a real :class:`~bluefog_tpu.fleet.FleetView`, and the
  real :class:`~bluefog_tpu.fleet.SLOEngine` replays over the simulated
  rollups (the ``bffleet-tpu --check`` shape).

The physics model (docs/sim.md has the full contract):

- **push-sum gossip** on a scalar state per rank: at a round boundary a
  rank consumes its mailbox, splits ``(x, p)`` uniformly over itself and
  its current out-neighbors, and ships the shares over the
  :class:`~bluefog_tpu.sim.network.LinkModel`; mass never leaves the
  arrays, so the exact audit (``sum(x) == injected``, ``sum(p) ==
  admissions``) holds to float addition error through every fault;
- **fences**: the round boundary waits for the slowest of the round's
  acks (the live loop's flush-per-peer), which is how a slow host
  throttles its senders — and what a control plan's ring-spine penalty
  relieves;
- **failure detection** is sender-side: a send whose retries exhaust the
  link budget is ABANDONED (mass kept, peer held DEAD in evidence); a
  killed rank is healed out at the next evidence-epoch boundary, the
  detection deadline the live HealthBoard's silence threshold plays;
- **membership** changes only at boundaries: joins are queued and
  admitted at the next epoch barrier (warm-started from a live donor's
  de-biased state, the PR-6 snapshot warm-start), graceful leaves hand
  their entire ``(x, p)`` to their out-neighbors at their own round
  boundary (mass conserved, the drain-flag discipline);
- **evidence dissemination** is epoch-consistent: every live rank's
  epoch-``w`` decision reads the same canonicalized record set (the
  shared barrier directory made ideal — no torn records, no propagation
  delay; PR 8's torn-record fuzzers already cover that axis), which
  isolates the byte-convergence property the simulator asserts.  The
  one compute elision, stated plainly: with identical inputs and
  identical prior plans, ``decide_plan`` is pure — so the simulator
  runs the REAL decide on a deterministic sample of controllers
  (``decide_sample``, all of them in small fleets), asserts literal
  byte-equality across the sample, and installs the identical plan
  everywhere instead of recomputing it ``n`` more times.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Mapping, Optional, Sequence, Set, Tuple

import numpy as np

from bluefog_tpu.control.controller import CommController
from bluefog_tpu.control.evidence import Evidence, canonicalize
from bluefog_tpu.control.plan import CommPlan, ControlConfig
from bluefog_tpu.fleet.record import FleetRecord
from bluefog_tpu.fleet.slo import SLOEngine, SLOSpec, default_specs
from bluefog_tpu.fleet.view import FleetView
from bluefog_tpu.metrics.health import MixingTracker
from bluefog_tpu.metrics.registry import quantile as _quantile
from bluefog_tpu.sim.core import EventLoop, rng_for
from bluefog_tpu.sim.network import LinkModel
from bluefog_tpu.topology.graphs import Topology, heal, replan
# phase spans only: when the continuous profiler is armed these tag the
# sim's handlers as compute/gossip/publish for sample attribution; the
# context managers carry NO wall-clock reads, so determinism holds
from bluefog_tpu.tracing import recorder as _tr

__all__ = ["SimConfig", "FleetSim", "ST_HEALTHY", "ST_SUSPECT", "ST_DEAD"]

# the resilience health-state values, spelled locally exactly as
# bluefog_tpu.control.controller spells them (this package must not
# import the runtime back; the pairing is asserted by a test)
ST_HEALTHY, ST_SUSPECT, ST_DEAD = 0, 1, 2

_EWMA_ALPHA = 0.25


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """One simulated fleet's knobs (all times are VIRTUAL seconds).

    ``faults`` maps host rank -> a chaos spec (the
    :mod:`bluefog_tpu.chaos.spec` grammar, verbatim): socket rules hit
    that host's simulated transport, rank rules schedule kills/leaves/
    stalls/joins.  ``compute_scale`` maps rank -> a persistent
    round-compute multiplier (the straggler profile the chaos grammar
    has no spelling for).  ``decide_sample`` bounds how many real
    ``decide_plan`` calls run per epoch (byte-equality is asserted
    across the sample; small fleets decide on every rank)."""

    n_ranks: int
    seed: int = 0
    capacity: Optional[int] = None
    initial_members: Optional[Sequence[int]] = None
    base_round_s: float = 0.01
    compute_jitter: float = 0.05
    latency_s: float = 0.002
    rto_s: float = 0.02
    link_budget_s: float = 0.25
    control: bool = False
    control_cfg: Optional[ControlConfig] = None
    evidence_every: int = 8
    fleet_every: int = 4
    decide_sample: int = 8
    faults: Mapping[int, str] = dataclasses.field(default_factory=dict)
    compute_scale: Mapping[int, float] = dataclasses.field(
        default_factory=dict)
    max_events: int = 8_000_000

    def __post_init__(self):
        if self.n_ranks < 2:
            raise ValueError("n_ranks must be >= 2")
        if self.evidence_every < 1 or self.fleet_every < 1:
            raise ValueError("cadences must be >= 1")
        if self.base_round_s <= 0:
            raise ValueError("base_round_s must be > 0")


class FleetSim:
    """See the module docstring.  Construct, optionally schedule
    scenario actions (:meth:`join` / :meth:`request_leave` /
    :meth:`kill` / :meth:`set_partition` / :meth:`set_compute_scale` /
    :meth:`set_host_faults` via ``loop.at``), then :meth:`run`."""

    def __init__(self, cfg: SimConfig):
        self.cfg = cfg
        cap = int(cfg.capacity or cfg.n_ranks)
        members = sorted(int(r) for r in (
            cfg.initial_members if cfg.initial_members is not None
            else range(cfg.n_ranks)))
        if not members or members[-1] >= cap:
            raise ValueError("initial members must fit the capacity")
        self.capacity = cap
        self.loop = EventLoop()
        self.links = LinkModel(latency_s=cfg.latency_s, rto_s=cfg.rto_s,
                               budget_s=cfg.link_budget_s, seed=cfg.seed)
        for r, spec in sorted(dict(cfg.faults).items()):
            self.links.set_host_faults(r, spec)
        self.compute_scale: Dict[int, float] = dict(cfg.compute_scale)

        # ---- per-slot state (plain lists: scalar hot path) ----
        self.x = [0.0] * cap
        self.p = [0.0] * cap
        self.mx = [0.0] * cap          # in-flight mailbox (x shares)
        self.mp = [0.0] * cap
        self.alive = [False] * cap
        self.round_no = [0] * cap
        self._dis_last = [float("nan")] * cap
        self._round_samples: List[List[float]] = [[] for _ in range(cap)]
        self._last_recv: List[Dict[int, float]] = [{} for _ in range(cap)]
        self._ack_ewma: List[Dict[int, float]] = [{} for _ in range(cap)]
        self._retx_total: List[Dict[int, int]] = [{} for _ in range(cap)]
        self._peer_state: List[Dict[int, int]] = [{} for _ in range(cap)]
        self._dead_view: List[Set[int]] = [set() for _ in range(cap)]
        self._pending_stall = [0.0] * cap
        self._leave_requested: Set[int] = set()
        # graceful leavers whose trailing in-flight deposits forward to
        # a live member (the drain fence's conservation, kept exact)
        self._forward_to: Dict[int, int] = {}
        self._compute_rng = [rng_for("compute", cfg.seed, r)
                             for r in range(cap)]

        # ---- audit ledgers ----
        self.injected_x = 0.0
        self.admitted_p = 0.0
        self.admissions = 0
        self.leaves = 0
        self.deaths = 0
        # mass inside DELIVER events still queued at audit time (sends
        # already deducted from the sender, not yet in a mailbox)
        self._inflight_x = 0.0
        self._inflight_p = 0.0

        # ---- the real decision stack ----
        self.ccfg = cfg.control_cfg or ControlConfig()
        self.ctl: Dict[int, CommController] = {}
        self.plan = CommPlan(codec_level=self.ccfg.max_codec_level)
        self.plan_changes = 0
        self.plan_divergences = 0
        self._ev_store: Dict[int, Evidence] = {}
        self._ev_round: Dict[int, int] = {}
        self._epoch_decided = 0
        self._corpses: Set[int] = set()
        self._healed: Set[int] = set()
        self._left_done: Set[int] = set()
        self._pending_joins: List[int] = []

        seed_topo = Topology(weights=np.eye(cap), name="sim")
        self.topo = replan(seed_topo, members)
        self._rebuild_adjacency()
        # the tracker is fed once per epoch = evidence_every rank
        # steps, each of which gossips (gossip_every=1 at launch), so
        # the prediction exponent starts at evidence_every
        self.tracker = MixingTracker(
            self.topo, rounds_per_update=cfg.evidence_every)
        self._mixing_excess: Optional[float] = None
        self._d0: Optional[float] = None  # initial consensus distance
        self.max_name_len = len(self.topo.name)
        self.connectivity_ok = True

        self.view = FleetView()
        self.spread_history: List[Tuple[float, float, float]] = []

        for r in members:
            self._activate(r, x0=self._draw_x0(r))
        # ranks whose next-epoch evidence the barrier still awaits (the
        # O(1)-per-publish arrival counter; rebuilt after every barrier)
        self._await_left: Set[int] = set(members)
        # stagger first rounds inside one nominal round so the fleet is
        # honestly asynchronous from t=0
        for r in members:
            start = self._compute_rng[r].random() * cfg.base_round_s
            self.loop.at(start, self._round_fn(r))
        self._arm_timed_faults()
        # spread sampling on a fixed virtual-time grid (2 nominal
        # rounds), so time-to-target resolution is independent of the
        # epoch-barrier cadence a straggler stretches
        self._sample_dt = 2.0 * cfg.base_round_s
        self.loop.at(self._sample_dt, self._sample_tick)

    def _sample_tick(self) -> None:
        self._sample_spread()
        self.loop.at(self.loop.now + self._sample_dt, self._sample_tick)

    # ------------------------------------------------------------ plumbing
    def _draw_x0(self, r: int) -> float:
        return rng_for("x0", self.cfg.seed, r).uniform(-1.0, 1.0)

    def _round_fn(self, r: int):
        return lambda: self._round(r)

    def _activate(self, r: int, *, x0: float) -> None:
        # ACCUMULATE, never overwrite: a rejoining leaver whose drain
        # handoff was partly abandoned (partition at leave time) still
        # holds residual ledgered (x, p) in its slot — the admission
        # adds the warm-start value and one unit of weight on top, so
        # arrays and ledgers move by exactly the same amount and the
        # exact audit survives a failed-drain rejoin
        self.x[r] += float(x0)
        self.p[r] += 1.0
        self.alive[r] = True
        self.injected_x += float(x0)
        self.admitted_p += 1.0
        self.admissions += 1
        self.ctl[r] = CommController(r, self.capacity, config=self.ccfg)
        self.ctl[r].plan = self.plan

    def _arm_timed_faults(self) -> None:
        for host in sorted(self.cfg.faults):
            self._check_rank_rule_placement(host)
            self._arm_box(host, base_t=0.0)

    def _check_rank_rule_placement(self, host: int) -> None:
        """A ``rank<N>`` rule filed under a DIFFERENT host's entry
        would never be consulted by rank N's round handler — refuse it
        loudly (the inert-rule posture ``read``/``sub`` already get)."""
        box = self.links.host_box(host)
        if box is None:
            return
        stray = sorted({r.rank for r in box.rules
                        if r.site == "rank" and r.rank != host})
        if stray:
            raise ValueError(
                f"faults entry for host {host} carries rank rules for "
                f"rank(s) {stray}: rank faults must live under their "
                "own rank's entry (a misplaced rule would sit silently "
                "inert and make scenario predicates vacuous)")

    def _arm_box(self, host: int, *, base_t: float) -> None:
        """Schedule a box's ``after_s`` rank rules on the virtual clock
        (offsets relative to ``base_t`` — construction time for config
        faults, installation time for mid-run installs, the live
        injector's ``arm()`` semantics).  Each armed closure re-checks
        that ITS box is still the host's installed one before firing,
        so replacing a spec genuinely cancels the superseded schedule
        (heap entries cannot be deleted; stale ones become no-ops)."""
        box = self.links.host_box(host)
        if box is None:
            return

        def guarded(action):
            def fire():
                if self.links.host_box(host) is box:
                    action()
            return fire

        for rule in box.timed_faults(host):
            rk = int(rule.rank)
            at = base_t + rule.after_s
            if rule.fault == "join":
                self.loop.at(at, guarded(
                    (lambda j: lambda: self.join(j))(rk)))
            elif rule.fault in ("die", "sigkill"):
                self.loop.at(at, guarded(
                    (lambda j: lambda: self.kill(j))(rk)))
            elif rule.fault == "leave":
                self.loop.at(at, guarded(
                    (lambda j: lambda: self.request_leave(j))(rk)))
            else:  # stall / sigstop: consumed at the next boundary
                dur = rule.s if rule.s > 0 else (rule.for_s or 0.0)
                self.loop.at(at, guarded(
                    (lambda j, d: lambda: self._add_stall(j, d))(
                        rk, dur)))

    def _add_stall(self, r: int, dur: float) -> None:
        self._pending_stall[r] += float(dur)

    def _rebuild_adjacency(self) -> None:
        w = self.topo.weights
        pos = w > 0.0
        np.fill_diagonal(pos, False)
        # plain-int adjacency: numpy int64 keys make every hot-path dict
        # op hash a numpy scalar — at 10^6 sends that is most of the run
        self._adj_out = [[int(v) for v in np.nonzero(pos[:, r])[0]]
                         for r in range(self.capacity)]
        self._adj_in = [[int(v) for v in np.nonzero(pos[r, :])[0]]
                        for r in range(self.capacity)]

    # --------------------------------------------------- scenario actions
    def members(self) -> List[int]:
        return [r for r in range(self.capacity) if self.alive[r]]

    def join(self, r: int) -> None:
        """Queue slot ``r`` to join; admitted at the next epoch barrier
        (round-boundary admission, the BF-RES002 discipline)."""
        r = int(r)
        if not self.alive[r] and r not in self._pending_joins:
            self._pending_joins.append(r)

    def request_leave(self, r: int) -> None:
        """Ask rank ``r`` for a graceful drain at its next round
        boundary (the ChaosLeave contract)."""
        self._leave_requested.add(int(r))

    def kill(self, r: int) -> None:
        """SIGKILL twin: the rank stops mid-flight — no drain, no final
        publish; its frozen ``(x, p)`` is the written-off mass and its
        peers discover by silence."""
        r = int(r)
        if not self.alive[r]:
            return
        self.alive[r] = False
        self._corpses.add(r)
        self.deaths += 1
        self._await_left.discard(r)
        self._check_barrier()

    def set_partition(self, cut_pairs) -> None:
        self.links.set_partition(cut_pairs)
        if not cut_pairs:
            # reachability restored: let senders re-probe immediately
            for dv in self._dead_view:
                dv.clear()

    def set_compute_scale(self, r: int, mult: float) -> None:
        self.compute_scale[int(r)] = float(mult)

    def set_host_faults(self, r: int, spec) -> None:
        """Install (or replace) one host's chaos rules mid-run; timed
        (``after_s``) rank rules are armed RELATIVE TO NOW — the live
        injector's ``arm()`` semantics — so a schedule-installed fault
        can never be silently inert."""
        self.links.set_host_faults(int(r), spec)
        self._check_rank_rule_placement(int(r))
        self._arm_box(int(r), base_t=self.loop.now)

    # ------------------------------------------------------ the rank round
    def _round(self, r: int) -> None:
        if not self.alive[r]:
            return
        t = self.loop.now
        step = self.round_no[r]
        extra = self._pending_stall[r]
        self._pending_stall[r] = 0.0

        box = self.links.host_box(r)
        if box is not None:
            for rule in box.rank_faults_due(r, step):
                if rule.fault in ("die", "sigkill"):
                    self.kill(r)
                    return
                if rule.fault == "leave":
                    self._leave_now(r)
                    return
                # stall / sigstop freeze the loop for the stated time
                extra += rule.s if rule.s > 0 else (rule.for_s or 0.0)
        if r in self._leave_requested:
            self._leave_requested.discard(r)
            self._leave_now(r)
            return

        with _tr.span("round", "sim", round_=step):
            # ---- consume the mailbox (the observing consume) ----
            if self.mp[r] != 0.0 or self.mx[r] != 0.0:
                if self.mp[r] > 0 and self.p[r] > 0:
                    dis = abs(self.mx[r] / self.mp[r]
                              - self.x[r] / self.p[r])
                    self._dis_last[r] = dis
                    self.ctl[r].note_disagreement(dis)
                self.x[r] += self.mx[r]
                self.p[r] += self.mp[r]
                self.mx[r] = 0.0
                self.mp[r] = 0.0

            # ---- gossip (plan cadence) ----
            fence = 0.0
            if step % self.plan.gossip_every == 0:
                with _tr.span("gossip", "sim", round_=step):
                    fence = self._gossip(r, t)

            # ---- telemetry at boundaries ----
            nxt = step + 1
            if nxt % self.cfg.fleet_every == 0:
                with _tr.span("publish", "sim", round_=nxt):
                    self._publish_fleet(r, nxt, t)
            if nxt % self.cfg.evidence_every == 0:
                with _tr.span("publish", "sim", round_=nxt):
                    self._publish_evidence(r, nxt)

            comp = (self.cfg.base_round_s * self.compute_scale.get(r, 1.0)
                    * (1.0 + self.cfg.compute_jitter
                       * (2.0 * self._compute_rng[r].random() - 1.0)))
            dur = comp + extra + fence
            self._round_samples[r].append(dur)
            self.round_no[r] = nxt
            self.loop.at(t + dur, self._round_fn(r))

    def _gossip(self, r: int, t: float) -> float:
        """Split (x, p) over self + out-neighbors and ship the shares;
        returns the fence cost (slowest ack of the round)."""
        outs = self._adj_out[r]
        if not outs:
            return 0.0
        share = 1.0 / (len(outs) + 1)
        dead_view = self._dead_view[r]
        ewma = self._ack_ewma[r]
        retx = self._retx_total[r]
        states = self._peer_state[r]
        fence = 0.0
        deliveries: Dict[float, List[Tuple[int, float, float]]] = {}
        sent = 0
        links_send = self.links.send
        alive = self.alive
        xr = self.x[r]
        pr = self.p[r]
        dx = xr * share
        dp = pr * share
        inflight_x = 0.0
        inflight_p = 0.0
        for j in outs:
            if j in dead_view:
                continue
            out = links_send(r, j) if alive[j] else None
            if out is None or out.abandoned:
                # budget exhausted (or silent corpse): latch, keep the
                # mass, hold the peer DEAD in this rank's evidence
                fence = max(fence, self.links.budget_s)
                dead_view.add(j)
                ewma[j] = self.links.budget_s
                states[j] = ST_DEAD
                continue
            deliveries.setdefault(out.deliver_dt, []).append(
                (j, dx, dp))
            inflight_x += dx
            inflight_p += dp
            sent += 1
            prev = ewma.get(j)
            ewma[j] = (out.ack_dt if prev is None
                       else _EWMA_ALPHA * out.ack_dt
                       + (1.0 - _EWMA_ALPHA) * prev)
            if out.retries:
                retx[j] = retx.get(j, 0) + out.retries
            states[j] = ST_HEALTHY
            if out.ack_dt > fence:
                fence = out.ack_dt
        if sent:
            frac = share * sent
            self.x[r] = xr - xr * frac
            self.p[r] = pr - pr * frac
            self._inflight_x += inflight_x
            self._inflight_p += inflight_p
            for delay in sorted(deliveries):
                items = deliveries[delay]
                self.loop.at(
                    t + delay,
                    (lambda it: lambda: self._deliver(r, it))(items))
        return fence

    def _deliver(self, src: int,
                 items: List[Tuple[int, float, float]]) -> None:
        with _tr.span("apply", "sim"):
            t = self.loop.now
            fw = self._forward_to
            for j, dx, dp in items:
                # the heir may itself have drained since: walk the chain
                # (always toward a later-live rank, so it terminates)
                while fw and j in fw:
                    j = fw[j]
                self.mx[j] += dx
                self.mp[j] += dp
                self._inflight_x -= dx
                self._inflight_p -= dp
                # receiver-side freshness clock (the thread-mode lag twin)
                self._last_recv[j][src] = t

    # ----------------------------------------------------- graceful leave
    def _leave_now(self, r: int) -> None:
        """The drain protocol at this rank's own round boundary:
        consume the pending mailbox (the live protocol's fence makes it
        empty; the sim folds it in explicitly), hand the ENTIRE (x, p)
        to the out-neighbors, then deactivate — mass conserved,
        baseline unchanged (vs a corpse's write-off).  Deposits still
        in flight toward the leaver are forwarded to a live member at
        the next barrier (:attr:`_forward_to`)."""
        self.x[r] += self.mx[r]
        self.p[r] += self.mp[r]
        self.mx[r] = 0.0
        self.mp[r] = 0.0
        outs = [j for j in self._adj_out[r]
                if self.alive[j] and j not in self._dead_view[r]]
        if outs:
            share = 1.0 / len(outs)
            handed = 0
            for j in outs:
                out = self.links.send(r, j)
                if out.abandoned:
                    continue
                dx = self.x[r] * share
                dp = self.p[r] * share
                self._inflight_x += dx
                self._inflight_p += dp
                self.loop.at(
                    self.loop.now + out.deliver_dt,
                    (lambda it: lambda: self._deliver(r, it))(
                        [(j, dx, dp)]))
                handed += 1
            self.x[r] -= self.x[r] * share * handed
            self.p[r] -= self.p[r] * share * handed
        self.alive[r] = False
        self._left_done.add(r)
        self.leaves += 1
        self._await_left.discard(r)
        self._check_barrier()

    # -------------------------------------------------- telemetry publish
    def _publish_fleet(self, r: int, round_: int, t: float) -> None:
        samples = self._round_samples[r]
        self._round_samples[r] = []
        if samples:
            s = sorted(samples)
            stats = {"count": float(len(s)),
                     "mean": sum(s) / len(s),
                     "p50": _quantile(s, 0.50),
                     "p99": _quantile(s, 0.99),
                     "max": s[-1]}
        else:
            stats = {"count": 0.0}
        peers: Dict[int, Dict[str, float]] = {}
        for j, v in self._ack_ewma[r].items():
            peers[j] = {"lag": float(v)}
        z = self.x[r] / self.p[r] if self.p[r] > 0 else float("nan")
        self.view.add(FleetRecord(
            rank=r, round=int(round_), t=float(t), round_s=stats,
            mass=self.p[r], z_mean=z, dis=self._dis_last[r],
            peers=peers))

    def _publish_evidence(self, r: int, round_: int) -> None:
        # per-peer lag evidence is the WIRE channel only (the sender's
        # ack EWMA, folded here once per epoch rather than per send —
        # the hot-path batching): it names the slow HOST its senders
        # observe — the BENCH_control shape.  A receiver-side staleness
        # channel would convict the slow host's fenced SENDERS (the
        # cascade, not the cause) and dilute the slow set.
        ctl = self.ctl[r]
        states = self._peer_state[r]
        retx = self._retx_total[r]
        for j, ew in self._ack_ewma[r].items():
            ctl.note_peer(j, lag_s=ew, state=states.get(j, ST_HEALTHY),
                          reconnects_total=retx.get(j, 0))
        ctl.note_mixing_excess(self._mixing_excess)
        self._ev_store[r] = ctl.evidence(int(round_))
        self._ev_round[r] = int(round_)
        self._await_left.discard(r)
        self._check_barrier()

    # ------------------------------------------------- the epoch barrier
    def _check_barrier(self) -> None:
        e = self.cfg.evidence_every
        while not self._await_left:
            if not any(self.alive):
                return
            with _tr.span("control", "sim"):
                self._epoch_barrier(self._epoch_decided + 1)
            nxt = (self._epoch_decided + 1) * e
            self._await_left = {
                m for m in self.members()
                if self._ev_round.get(m, 0) < nxt}

    def _epoch_barrier(self, epoch: int) -> None:
        """The round-boundary rendezvous: heal corpses, admit joins,
        replan after leaves, decide + actuate the plan, re-anchor the
        mixing tracker, sample consensus spread.  Fires when the LAST
        live rank published epoch ``epoch``'s evidence — virtual time
        here is the straggler's publish time, which is honest."""
        e = self.cfg.evidence_every
        round_ = epoch * e
        topo_changed = False
        membership_changed = False

        # 1. heal discovered corpses (the detection deadline: one epoch)
        new_dead = self._corpses - self._healed
        if new_dead:
            membership_changed = True
            self.topo = heal(self.topo, self._corpses)
            self._healed |= new_dead
            topo_changed = True
            for r in sorted(new_dead):
                self._ev_store.pop(r, None)
                self._ev_round.pop(r, None)
            for ctl in self.ctl.values():
                for r in sorted(new_dead):
                    ctl.forget_peer(r)

        # 2. membership change: admissions + completed drains -> replan
        if self._pending_joins or self._left_done:
            membership_changed = True
            heir = next(iter(self.members()), None)
            for r in sorted(self._left_done):
                self._ev_store.pop(r, None)
                self._ev_round.pop(r, None)
                for ctl in self.ctl.values():
                    ctl.forget_peer(r)
                if heir is not None:
                    # stragglers that were in flight toward the leaver
                    # when it drained land on a live member instead —
                    # the fence's conservation, kept exact
                    self.mx[heir] += self.mx[r]
                    self.mp[heir] += self.mp[r]
                    self.mx[r] = 0.0
                    self.mp[r] = 0.0
                    self._forward_to[r] = heir
                    # path-compress earlier chains ending at r so the
                    # deliver-time walk stays short
                    for old, tgt in self._forward_to.items():
                        if tgt == r:
                            self._forward_to[old] = heir
            self._left_done.clear()
            joins = sorted(set(self._pending_joins))
            self._pending_joins = []
            donor_pool = self.members()
            for r in joins:
                if self.alive[r] or r in self._corpses:
                    continue
                self._forward_to.pop(r, None)  # rejoining leaver
                donor = donor_pool[0] if donor_pool else None
                x0 = (self.x[donor] / self.p[donor]
                      if donor is not None and self.p[donor] > 0
                      else self._draw_x0(r))
                self._activate(r, x0=x0)
                self._ev_round[r] = int(round_)  # admitted THIS epoch
                start = self.loop.now + (
                    self._compute_rng[r].random() * self.cfg.base_round_s)
                self.round_no[r] = int(round_)
                self.loop.at(start, self._round_fn(r))
            members = self.members()
            if members:
                self.topo = replan(self.topo, members)
                topo_changed = True  # the surface sweep below narrows
                # every controller to its new out-neighbors

        # 3. decide + actuate (control runs only)
        members = self.members()
        if self.cfg.control and members:
            records = canonicalize(self._ev_store.values())
            k = max(1, min(self.cfg.decide_sample, len(members)))
            sample = members[:k - 1] + [members[-1]] if k > 1 \
                else members[:1]
            blobs = set()
            plan0 = None
            for r in sample:
                plan_r = self.ctl[r].decide(int(round_), records)
                blobs.add(plan_r.to_bytes())
                plan0 = plan_r if plan0 is None else plan0
            if len(blobs) > 1:
                self.plan_divergences += 1
            if plan0 is not None:
                changed = plan0.version != self.plan.version
                self.plan = plan0
                for r in members:
                    self.ctl[r].plan = plan0
                if changed:
                    self.plan_changes += 1
                    # the actuation: the plan's penalized mixing graph
                    # over the current members (real replan_penalized
                    # via the real primitive, gauges and all)
                    self.topo = self.ctl[sample[0]].apply_plan(
                        topology=self.topo, members=members)
                    topo_changed = True

        if topo_changed:
            self._rebuild_adjacency()
            # the observation-surface sweep (the retain_peers/
            # forget_peer discipline): a rank whose edge to a peer the
            # plan just dropped must stop republishing its FROZEN last
            # observation — a stale 250 ms lag would keep convicting a
            # peer only its ring-pred still actually measures
            for r in self.members():
                allowed = set(self._adj_out[r])
                for table in (self._ack_ewma[r], self._peer_state[r],
                              self._retx_total[r]):
                    for j in [j for j in table if j not in allowed]:
                        del table[j]
                self.ctl[r].retain_peers(allowed)
            # gossip happens on steps divisible by gossip_every, so an
            # epoch of e rank-steps contains e / gossip_every gossip
            # rounds — the exponent DIVIDES when the controller
            # stretches the cadence (the live loops' rpu arithmetic;
            # multiplying would predict |λ2|^(e·g) and read a healthy
            # stretched fleet as a huge mixing excess)
            self.tracker.rebase(
                self.topo,
                rounds_per_update=max(1, e // self.plan.gossip_every))
            self.max_name_len = max(self.max_name_len,
                                    len(self.topo.name))
            self.connectivity_ok = (self.connectivity_ok
                                    and self._strongly_connected())
        if membership_changed:
            # the cross-boundary contraction ratio compares distances
            # over DIFFERENT member sets — a join reads as a mixing
            # failure and marches the densify ladder toward the
            # fully-connected top rung (at 1000 ranks, a million-edge
            # plan).  The rebase re-anchored the prediction; this
            # re-anchors the measurement stream.
            self.tracker.reset_measurement()
            self._mixing_excess = None

        # 4. mixing health on the simulated consensus state — only
        # while the distance is far from float noise (the mixing.py
        # floor discipline): a fully mixed fleet's ratio is numerical
        # garbage that would read as a huge excess and false-alarm the
        # densify ladder
        d = self._consensus_l2()
        if self._d0 is None and d > 0:
            self._d0 = d
        if d > 1e-12 * max(self._d0 or 1.0, 1.0):
            meas = self.tracker.update(d)
            if meas is not None and self.tracker.predicted is not None:
                self._mixing_excess = meas - self.tracker.predicted
        else:
            self.tracker.reset_measurement()
            self._mixing_excess = None
        # bounded re-probe: one abandoned-send retry per edge per epoch
        # (the Backoff cadence) — a healed partition is rediscovered
        # within an epoch, a still-dead peer costs one budget per epoch
        for dv in self._dead_view:
            dv.clear()
        self._epoch_decided = epoch

    # ----------------------------------------------------------- queries
    def _consensus_l2(self) -> float:
        zs = [self.x[r] / self.p[r] for r in self.members()
              if self.p[r] > 0]
        if not zs:
            return 0.0
        mean = sum(zs) / len(zs)
        return math.sqrt(sum((z - mean) ** 2 for z in zs))

    def _zstar(self) -> float:
        """The live set's consensus fixed point: total live (x + in
        flight) over total live weight."""
        live = self.members()
        tx = sum(self.x[r] + self.mx[r] for r in live)
        tp = sum(self.p[r] + self.mp[r] for r in live)
        return tx / tp if tp > 0 else float("nan")

    def _sample_spread(self) -> None:
        zstar = self._zstar()
        errs = sorted(abs(self.x[r] / self.p[r] - zstar)
                      for r in self.members() if self.p[r] > 0)
        if not errs:
            return
        med = _quantile(errs, 0.50)
        self.spread_history.append((self.loop.now, med, errs[-1]))

    def _strongly_connected(self) -> bool:
        live = self.members()
        if len(live) <= 1:
            return True
        idx = {r: i for i, r in enumerate(live)}
        fwd = [[idx[j] for j in self._adj_out[r] if self.alive[j]]
               for r in live]
        rev = [[idx[j] for j in self._adj_in[r] if self.alive[j]]
               for r in live]

        def reach(adj) -> bool:
            seen = [False] * len(live)
            seen[0] = True
            frontier = [0]
            n = 1
            while frontier:
                nxt = []
                for u in frontier:
                    for v in adj[u]:
                        if not seen[v]:
                            seen[v] = True
                            n += 1
                            nxt.append(v)
                frontier = nxt
            return n == len(live)

        return reach(fwd) and reach(rev)

    def audit(self) -> Tuple[float, float]:
        """(x error, p error) of the exact conservation ledgers over ALL
        slots — mass never leaves the arrays, so both are float-addition
        noise no matter what faults ran."""
        tx = sum(self.x) + sum(self.mx) + self._inflight_x
        tp = sum(self.p) + sum(self.mp) + self._inflight_p
        return tx - self.injected_x, tp - self.admitted_p

    def plans_converged(self) -> bool:
        blobs = {self.ctl[r].plan.to_bytes() for r in self.members()}
        return len(blobs) <= 1

    def time_to_target(self, eps: float, *,
                       metric: str = "median") -> Optional[float]:
        """First virtual time the consensus error fell below ``eps``
        (None if never).  ``metric``: ``median`` ignores a straggling
        tail (the BENCH_control posture — time-to-target of the healthy
        majority); ``max`` is the strict spread."""
        col = 1 if metric == "median" else 2
        for entry in self.spread_history:
            if entry[col] < eps:
                return entry[0]
        return None

    def time_to_rounds(self, k: int,
                       quantile: float = 0.5) -> Optional[float]:
        """Virtual time at which the ``quantile`` rank completed ``k``
        rounds (from the round-stamped fleet records; resolution is the
        ``fleet_every`` publish cadence).  This is the STEP-THROUGHPUT
        time-to-target — in the DSGD model every round is a local
        optimizer step, so "the median rank has taken K steps" is the
        simulated twin of the live bench's loss-target clock; consensus
        health is asserted separately.  None when fewer than
        ``quantile`` of the ranks ever got there."""
        times: List[float] = []
        ranks = self.view.ranks()
        rounds_at_or_after = [rd for rd in self.view.rounds()
                              if rd >= k]
        for rank in ranks:
            best = None
            for rd in rounds_at_or_after:
                rec = self.view.record(rank, rd)
                if rec is not None and (best is None or rec.t < best):
                    best = rec.t
            if best is not None:
                times.append(best)
        if not ranks:
            return None
        times.sort()
        need = int(len(ranks) * quantile) + 1
        if len(times) < need:
            return None
        return times[need - 1]

    def replay_slos(self, specs: Optional[Sequence[SLOSpec]] = None
                    ) -> SLOEngine:
        """Replay the simulated fleet records through a real
        :class:`SLOEngine` (the ``bffleet-tpu --check`` shape) and
        return the engine (transitions, worst state, attributions)."""
        engine = SLOEngine(tuple(specs) if specs else default_specs())
        engine.advance(self.view)
        return engine

    # --------------------------------------------------------------- run
    def run(self, horizon_s: float) -> None:
        """Run the event loop to the bounded virtual-time horizon."""
        if horizon_s <= 0:
            raise ValueError("horizon_s must be > 0 (every scenario "
                             "declares a bounded virtual-time horizon)")
        self.loop.run(until=float(horizon_s),
                      max_events=self.cfg.max_events)
