"""The simulated network: links, faults, and the ack/fence arithmetic.

Faults are expressed in the ONE chaos spec grammar
(:mod:`bluefog_tpu.chaos.spec`) and interpreted here against virtual
traffic: a :class:`FaultBox` mirrors the live injector's trigger
semantics exactly — per-rule frame counters (``after_frames``/
``every``), seeded per-rule coins (``prob``/``rate``), ``times`` caps —
so ``server:delay:ms=120:rate=0.95`` means the same thing to a 3-rank
live run under ``BLUEFOG_TPU_CHAOS`` and to a 1000-rank simulated one.
Each simulated host owns a box (the live injector is per-process too);
``server``/``ack`` sites evaluate on the DESTINATION host's box (frames
into its window server, acks out of it), ``client`` on the sender's.

The deposit model is the PR-4/5 transport collapsed to arithmetic: a
deposit is reliable (the real `DepositStream` retains payload snapshots
and replays under a bounded Backoff), so a dropped or truncated frame
costs a retransmit timeout, never lost mass.  :meth:`LinkModel.send`
computes the whole exchange at send time — delivery delay, ack
round-trip (the fence cost the sender's round boundary pays), retry
count — and a retry budget exceeded reports the send ABANDONED: the
sender keeps the mass snapshot (nothing was acked) and marks the peer
DEAD, which is precisely the live stream's budget-exhaustion latch.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, List, Optional, Tuple

from bluefog_tpu.chaos.spec import Rule, parse_spec
from bluefog_tpu.sim.core import rng_for

__all__ = ["FaultBox", "LinkModel", "SendOutcome"]


class FaultBox:
    """One simulated host's chaos rules, with the live injector's
    trigger semantics (counters, seeded coins, fire caps) evaluated
    against virtual frames.  Single-threaded by construction — the
    event loop serializes everything — so no lock."""

    def __init__(self, host: int, rules, *, seed: int = 0):
        if isinstance(rules, str):
            rules = parse_spec(rules)
        self.host = int(host)
        self.rules: List[Rule] = list(rules)
        self._counters = [0] * len(self.rules)
        self._fired = [0] * len(self.rules)
        self._rngs = [rng_for("chaos", seed, self.host, r.seed, i)
                      for i, r in enumerate(self.rules)]

    def fire(self, site: str) -> Optional[Tuple]:
        """The injector's ``fire`` contract on virtual traffic: count
        this frame for every matching socket rule and return the first
        triggered action — ``('drop',) | ('truncate',) | ('delay', s) |
        ('stall', s)`` — or None."""
        action: Optional[Tuple] = None
        for i, r in enumerate(self.rules):
            if r.site != site and r.site != "any":
                continue
            self._counters[i] += 1
            if action is not None:
                continue  # keep counting other rules
            mx = r.max_fires()
            if mx and self._fired[i] >= mx:
                continue
            hit = True
            if r.after_frames is not None:
                hit = self._counters[i] == r.after_frames
            elif r.every is not None:
                hit = self._counters[i] % max(r.every, 1) == 0
            elif r.prob is not None:
                hit = self._rngs[i].random() < r.prob
            elif r.rate is not None:
                hit = self._rngs[i].random() < r.rate
            if not hit:
                continue
            self._fired[i] += 1
            if r.fault == "drop":
                action = ("drop",)
            elif r.fault == "truncate":
                action = ("truncate",)
            elif r.fault == "delay":
                action = ("delay", r.ms / 1000.0)
            else:  # stall
                action = ("stall", r.s)
        return action

    def rank_faults_due(self, rank: int, step: int) -> List[Rule]:
        """Matured ``at_step`` rank rules for this host (``check_step``
        semantics: fires once per rule, at the first round boundary at
        or after ``at_step``)."""
        due: List[Rule] = []
        for i, r in enumerate(self.rules):
            if r.site != "rank" or r.rank != rank or r.at_step is None:
                continue
            mx = r.max_fires()
            if mx and self._fired[i] >= mx:
                continue
            if step >= r.at_step:
                self._fired[i] += 1
                due.append(r)
        return due

    def timed_faults(self, rank: int) -> List[Rule]:
        """``after_s`` rank rules for this host (the simulator schedules
        them on the virtual clock — the event-loop twin of the
        injector's daemon timers)."""
        return [r for r in self.rules
                if r.site == "rank" and r.rank == rank
                and r.after_s is not None]


@dataclasses.dataclass(frozen=True)
class SendOutcome:
    """One deposit exchange, fully resolved at send time.

    ``deliver_dt`` — virtual seconds until the payload lands in the
    destination mailbox; ``ack_dt`` — seconds until the sender holds the
    ack (>= deliver_dt; the round boundary's fence cost — the live
    loop's flush-per-peer); ``retries`` — retransmissions the exchange
    needed; ``abandoned`` — the retry budget ran out (nothing
    delivered, mass stays with the sender, peer marked DEAD)."""

    deliver_dt: float
    ack_dt: float
    retries: int
    abandoned: bool = False


_ABANDONED = SendOutcome(deliver_dt=0.0, ack_dt=0.0, retries=0,
                         abandoned=True)


class LinkModel:
    """Latency, loss, and reachability between simulated hosts.

    ``latency_s`` is the one-way base latency; ``rto_s`` the retransmit
    timeout a lost frame costs; ``budget_s`` the per-send retry budget
    (the live ``Backoff`` ctor REFUSES unbounded budgets — so does the
    simulator: ``budget_s`` is mandatory and positive).  ``partition``
    is a set of ordered ``(src, dst)`` pairs whose DIRECTION is
    severed.  A severed direction kills everything that must traverse
    it — payloads of ``src -> dst`` sends AND acks of ``dst -> src``
    sends — so one ordered pair abandons both flows over the link,
    exactly as a one-direction fiber cut stalls both TCP flows live;
    :meth:`cut_between` spells a full bidirectional partition."""

    def __init__(self, *, latency_s: float = 0.002, rto_s: float = 0.02,
                 budget_s: float = 0.25, seed: int = 0):
        if budget_s <= 0:
            raise ValueError(
                "budget_s must be > 0: an unbounded retry budget is the "
                "unbounded-reconnect loop BF-RES001 forbids live, and it "
                "would wedge a simulated sender the same way")
        self.latency_s = float(latency_s)
        self.rto_s = float(rto_s)
        self.budget_s = float(budget_s)
        self.seed = int(seed)
        self._boxes: Dict[int, FaultBox] = {}
        self.partition: FrozenSet[Tuple[int, int]] = frozenset()
        # the fault-free fast path is one shared outcome object — at a
        # thousand ranks most sends hit it, and building a dataclass per
        # clean send is most of the event loop's cost
        self._clean = SendOutcome(deliver_dt=self.latency_s,
                                  ack_dt=2.0 * self.latency_s, retries=0)
        self._trivial = True  # no boxes, no partition: sends hit _clean

    # ------------------------------------------------------------- faults
    def set_host_faults(self, host: int, rules) -> None:
        """Install (or replace) one host's chaos rules — ``rules`` is a
        spec string or pre-parsed ``Rule`` list; an empty/None value
        clears the box.

        Sites the simulator cannot actuate are REFUSED rather than
        silently stored: the sim models the deposit path
        (``server``/``ack``/``client``, and ``any`` over those three) —
        a ``read``/``sub`` rule would parse, sit inert, and let a
        scenario's predicates pass vacuously over a fault that never
        fired."""
        if not rules:
            self._boxes.pop(int(host), None)
        else:
            if isinstance(rules, str):
                rules = parse_spec(rules)
            inert = sorted({r.site for r in rules
                            if r.site in ("read", "sub", "relay")})
            if inert:
                raise ValueError(
                    f"chaos site(s) {inert} are read-path faults the "
                    "simulator does not model (it simulates the "
                    "deposit path: server/ack/client/any and rank "
                    "faults); a silently inert rule would make the "
                    "scenario's acceptance predicates vacuous")
            self._boxes[int(host)] = FaultBox(int(host), rules,
                                              seed=self.seed)
        self._trivial = not self._boxes and not self.partition

    def host_box(self, host: int) -> Optional[FaultBox]:
        return self._boxes.get(int(host))

    def set_partition(self, cut_pairs) -> None:
        """Install the current unreachable ``(src, dst)`` set (empty =
        fully reachable)."""
        self.partition = frozenset(
            (int(a), int(b)) for a, b in (cut_pairs or ()))
        self._trivial = not self._boxes and not self.partition

    @staticmethod
    def cut_between(group_a, group_b):
        """The ordered pair set that severs two rank groups BOTH ways —
        the partition-scenario helper."""
        a, b = [int(r) for r in group_a], [int(r) for r in group_b]
        return frozenset((x, y) for x in a for y in b) | frozenset(
            (y, x) for x in a for y in b)

    # --------------------------------------------------------------- send
    def send(self, src: int, dst: int) -> SendOutcome:
        """Resolve one deposit ``src -> dst``: returns the
        :class:`SendOutcome` (see class docstring).  Deterministic given
        the model seed and the frame history both hosts' boxes have
        seen."""
        if self._trivial:
            return self._clean
        if (src, dst) in self.partition or (dst, src) in self.partition:
            # unreachable in EITHER direction: a forward cut loses the
            # payload, a reverse-only cut loses every ack — live, both
            # burn the sender's whole budget and latch (the sim's
            # documented applied-but-unacked convention resolves the
            # reverse case conservatively as not-applied)
            return _ABANDONED
        sbox = self._boxes.get(int(src))
        dbox = self._boxes.get(int(dst))
        if sbox is None and dbox is None:
            return self._clean
        waited = 0.0
        retries = 0
        while True:
            leg = self.latency_s
            lost = False
            if sbox is not None:
                act = sbox.fire("client")
                if act is not None:
                    if act[0] in ("drop", "truncate"):
                        lost = True
                    else:  # delay / stall
                        leg += act[1]
            if not lost and dbox is not None:
                act = dbox.fire("server")
                if act is not None:
                    if act[0] in ("drop", "truncate"):
                        lost = True
                    else:
                        leg += act[1]
            if lost:
                waited += self.rto_s
                retries += 1
                if waited > self.budget_s:
                    return _ABANDONED
                continue
            deliver_dt = waited + leg
            # ack leg: a lost ack re-sends the (already applied) batch
            # after an RTO; the owner dedups by seq, so only the fence
            # cost grows (the applied-but-unacked ambiguity, resolved
            # exactly as op-6 STREAM_ATTACH does live)
            ack_wait = 0.0
            while True:
                ack_leg = self.latency_s
                ack_lost = False
                if dbox is not None:
                    act = dbox.fire("ack")
                    if act is not None:
                        if act[0] in ("drop", "truncate"):
                            ack_lost = True
                        else:
                            ack_leg += act[1]
                if ack_lost:
                    ack_wait += self.rto_s
                    retries += 1
                    if waited + leg + ack_wait > self.budget_s:
                        # nothing acked: the live sender retains the
                        # snapshot and latches; the sim keeps the mass.
                        # (The batch may have APPLIED owner-side; the
                        # sim resolves the ambiguity conservatively as
                        # not-applied — the replay path's dedup makes
                        # both answers equivalent for the audit.)
                        return _ABANDONED
                    continue
                break
            return SendOutcome(deliver_dt=deliver_dt,
                               ack_dt=deliver_dt + ack_wait + ack_leg,
                               retries=retries)
