"""Simulated-vs-closed-form mixing: the spectral-gap fidelity check.

The paper's core claim (arXiv:2111.04287) is that topology choice
governs convergence through the mixing matrix's second eigenvalue: one
synchronous gossip round contracts disagreement by ``|lambda_2(W)|``.
This module runs EXACTLY that experiment on a 1-D consensus state at
scales the container can never run live (n = 1024 is a millisecond of
numpy, not a thousand sockets), with the package's REAL measurement
stack in the loop:

- the prediction comes from :func:`bluefog_tpu.analysis.topology_check.
  spectral_gap` via a real :class:`bluefog_tpu.metrics.health.
  MixingTracker` (the same object the live loops feed);
- the measurement is the tracker's measured-contraction stream over the
  simulated rounds.

The per-round ratio ``d_t / d_{t-1}`` oscillates for matrices with
complex or negative subdominant eigenvalues (exp2 is non-normal), so
the headline number is the GEOMETRIC-MEAN contraction over the window —
``(d_T / d_0)^(1/T) -> |lambda_2|`` for generic initial conditions —
computed only while the distance is far from float noise (a fully
mixed state's ratio is garbage; the window stops before it).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import numpy as np

from bluefog_tpu.metrics.health import MixingTracker
from bluefog_tpu.sim.core import rng_for
from bluefog_tpu.topology.graphs import Topology

__all__ = ["MixingRun", "run_sync_mixing"]

#: stop measuring once consensus distance falls below this times the
#: initial distance — beyond it the ratio measures float cancellation,
#: not mixing
_FLOOR_FRAC = 1e-10


@dataclasses.dataclass(frozen=True)
class MixingRun:
    """One synchronous-gossip fidelity run."""

    n: int
    rounds_used: int
    predicted: float          # |lambda_2(W)| from the real tracker
    measured_geomean: float   # (d_T / d_0)^(1/T) over the usable window
    final_distance: float
    initial_distance: float

    @property
    def excess(self) -> float:
        """measured minus predicted (the tracker's alarm axis)."""
        return self.measured_geomean - self.predicted


def run_sync_mixing(topo: Topology, *, rounds: int = 200,
                    seed: int = 0,
                    tracker: Optional[MixingTracker] = None) -> MixingRun:
    """Synchronous gossip ``x <- W x`` on a seeded 1-D state, measured
    by a real :class:`MixingTracker` against its own spectral-gap
    prediction.

    Returns the :class:`MixingRun`; ``measured_geomean`` is NaN when
    the state mixed to the float floor before a single usable round
    (a fully connected graph averages exactly in one step — assert on
    ``final_distance`` instead there)."""
    if rounds < 1:
        raise ValueError("rounds must be >= 1")
    w = np.asarray(topo.weights, dtype=np.float64)
    n = w.shape[0]
    rng = rng_for("mixing", seed, n, topo.name)
    x = np.array([rng.uniform(-1.0, 1.0) for _ in range(n)],
                 dtype=np.float64)
    # the consensus limit of row-stochastic gossip is the left-Perron
    # weighted mean, not the plain mean; measure distance to the plain
    # mean's subspace complement the tracker way: ||x - mean(x)|| is
    # what the live loops feed, and it contracts at |lambda_2| all the
    # same (the mean component may drift, the disagreement still dies)
    tracker = tracker if tracker is not None else MixingTracker(topo)
    if tracker.predicted is None:
        tracker.rebase(topo)
    predicted = float(tracker.predicted if tracker.predicted is not None
                      else float("nan"))

    def dist(v: np.ndarray) -> float:
        return float(np.linalg.norm(v - v.mean()))

    d0 = dist(x)
    tracker.update(d0)
    dists = [d0]
    d = d0
    for _ in range(rounds):
        x = w @ x
        d = dist(x)
        tracker.update(d)
        if d0 <= 0 or d <= _FLOOR_FRAC * d0:
            break
        dists.append(d)
    used = len(dists) - 1
    if used == 0:
        geomean = float("nan")
    else:
        # burn-in: the first third of the usable window still carries
        # the fast transient modes a generic start excites — the
        # asymptotic |lambda_2| rate only shows once they died
        b = min(used // 3, 50)
        geomean = float(math.exp(
            math.log(dists[used] / dists[b]) / (used - b)))
    return MixingRun(n=n, rounds_used=used, predicted=predicted,
                     measured_geomean=geomean, final_distance=d,
                     initial_distance=d0)
