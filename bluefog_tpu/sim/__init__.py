"""Fleet digital twin: a deterministic discrete-event simulator that
runs the package's REAL decision code at 1000+ simulated ranks.

Every live check in this repo tops out at 3-4 ranks in one container;
the paper's claims (arXiv:2111.04287) are about what topology and
asynchrony do at fleet scale.  The pieces needed to go bigger without
sockets already existed — ``decide_plan`` is pure and byte-convergent,
``replan``/``replan_penalized``/``heal`` are deterministic and
memoryless, Evidence and FleetRecord are canonical JSON, and the chaos
grammar describes faults declaratively — this package composes them
under a virtual clock:

- :mod:`~bluefog_tpu.sim.core` — event loop, virtual clock, seeded RNG
  derivation (no wall clock, no ambient RNG: the BF-SIM001 contract);
- :mod:`~bluefog_tpu.sim.network` — links with latency/loss/straggler
  profiles expressed in the ONE chaos spec grammar
  (:mod:`bluefog_tpu.chaos.spec`);
- :mod:`~bluefog_tpu.sim.mixing` — synchronous spectral-gap fidelity:
  simulated contraction vs the real MixingTracker's |lambda_2|;
- :mod:`~bluefog_tpu.sim.fleet` — the event-driven push-sum fleet over
  the real ``CommController``/``decide_plan``, ``replan``/``heal``, and
  ``SLOEngine`` code paths, with exact mass audits through churn;
- :mod:`~bluefog_tpu.sim.scenarios` — the table-driven scenario lab
  (diurnal autoscale, partition, flash crowd, cascading slow peers)
  with bounded horizons and explicit acceptance predicates;
- the ``bfsim-tpu`` CLI (:mod:`~bluefog_tpu.sim.cli`) — ``--check``
  runs the suite and exits nonzero on any failed predicate.

See docs/sim.md for the event model, the determinism contract, and the
scenario grammar.
"""

from bluefog_tpu.sim.core import EventLoop, derive_seed, rng_for
from bluefog_tpu.sim.fleet import FleetSim, SimConfig
from bluefog_tpu.sim.mixing import MixingRun, run_sync_mixing
from bluefog_tpu.sim.network import FaultBox, LinkModel, SendOutcome
from bluefog_tpu.sim.scenarios import (PREDICATES, SCENARIO_NAMES,
                                       Scenario, build_suite,
                                       run_scenario, run_suite)

__all__ = [
    "EventLoop",
    "FaultBox",
    "FleetSim",
    "LinkModel",
    "MixingRun",
    "PREDICATES",
    "SCENARIO_NAMES",
    "Scenario",
    "SendOutcome",
    "SimConfig",
    "build_suite",
    "derive_seed",
    "rng_for",
    "run_scenario",
    "run_suite",
    "run_sync_mixing",
]
