"""The discrete-event core: virtual clock, event queue, seeded RNG.

The determinism contract (docs/sim.md), stated plainly and enforced by
the BF-SIM001 lint over this package:

- **No wall clock.**  Time is the :class:`EventLoop`'s ``now`` — a
  float of virtual seconds that advances ONLY when the loop pops the
  next event.  Nothing in ``bluefog_tpu/sim/`` may call ``time.time``/
  ``time.monotonic``/``time.sleep``; a simulated second costs whatever
  the handlers cost, and the same scenario produces the same virtual
  trajectory on a loaded laptop and an idle server.
- **No ambient RNG.**  Every random draw comes from a
  ``random.Random`` seeded through :func:`derive_seed` — a stable FNV-1a
  fold of the scenario seed and a structural name (``"link:3:7"``,
  ``"compute:42"``), so adding a new consumer never perturbs existing
  streams (the seeded-chaos discipline: per-rule RNGs, not one shared
  stream whose consumption order is load-bearing).
- **Deterministic ordering.**  Events at equal virtual times pop in
  schedule order (a monotone sequence number breaks ties), so two runs
  with the same seed execute handlers in the same order and the
  scenario report is byte-identical.
"""

from __future__ import annotations

import heapq
import random
from typing import Callable, List, Optional, Tuple

__all__ = ["EventLoop", "derive_seed", "rng_for"]

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK = (1 << 64) - 1


def derive_seed(*parts) -> int:
    """A stable 64-bit seed from structural parts (ints/strings): FNV-1a
    over the parts' canonical byte spellings.  Pure and
    platform-independent — the same parts give the same seed on any
    Python, which is what makes scenario reports reproducible across
    machines."""
    h = _FNV_OFFSET
    for part in parts:
        data = str(part).encode() + b"\x1f"
        for b in data:
            h = ((h ^ b) * _FNV_PRIME) & _MASK
    return h


def rng_for(*parts) -> random.Random:
    """A fresh seeded ``random.Random`` for one named consumer (the only
    sanctioned RNG constructor inside the simulator)."""
    return random.Random(derive_seed(*parts))


class EventLoop:
    """A minimal deterministic discrete-event loop.

    Events are ``(time, seq, fn)`` on a heap; ``seq`` is a monotone
    schedule counter so same-time events pop in the order they were
    scheduled (no comparison ever reaches the callables).  ``now``
    advances monotonically — scheduling into the past is a bug and
    raises rather than silently reordering history."""

    def __init__(self):
        self.now = 0.0
        self._q: List[Tuple[float, int, Callable[[], None]]] = []
        self._seq = 0
        self.processed = 0

    def at(self, t: float, fn: Callable[[], None]) -> None:
        t = float(t)
        if t < self.now:
            raise ValueError(
                f"cannot schedule at t={t:.6f} before now={self.now:.6f}")
        heapq.heappush(self._q, (t, self._seq, fn))
        self._seq += 1

    def after(self, dt: float, fn: Callable[[], None]) -> None:
        if dt < 0:
            raise ValueError(f"negative delay {dt}")
        self.at(self.now + float(dt), fn)

    def __len__(self) -> int:
        return len(self._q)

    def run(self, until: Optional[float] = None,
            max_events: Optional[int] = None) -> int:
        """Pop and execute events in ``(time, seq)`` order until the
        queue is empty, the next event lies beyond ``until``, or
        ``max_events`` handlers ran (the runaway backstop every bounded
        scenario horizon relies on).  Returns the number of events
        executed by THIS call."""
        n = 0
        while self._q:
            if max_events is not None and n >= max_events:
                break
            t, _, fn = self._q[0]
            if until is not None and t > until:
                break
            heapq.heappop(self._q)
            self.now = t
            fn()
            n += 1
        if until is not None and self.now < until and (
                not self._q or self._q[0][0] > until):
            # the horizon itself is an observable point in virtual time
            self.now = until
        self.processed += n
        return n
