"""Reader-tree simulation: thousands of readers behind a relay tree.

The digital twin's read-path half: a deterministic discrete-event model
of one trainer publishing rounds at a fixed cadence into a
``degree``-ary relay tree ``depth`` tiers deep, with the leaf tier
fanning out to O(thousands) of readers.  It models exactly the
mechanisms :mod:`bluefog_tpu.relay` implements — per-hop skip-to-latest
(an edge carries at most one in-flight push; newer rounds overwrite the
pending one and count as skipped), strictly-forward landing (a node
drops rounds at or below its cursor), and re-parenting (a killed
relay's children re-attach to its parent after a reconnect delay,
cursor preserved) — on the virtual clock, so the tree's staleness and
delivery-cleanliness claims are checkable at a scale no live test
reaches.

Determinism: the BF-SIM001 contract — no wall clock, no ambient RNG;
per-edge latency jitter draws from :func:`~bluefog_tpu.sim.core.
rng_for` streams keyed by the edge's structural name.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from bluefog_tpu.sim.core import EventLoop, rng_for

__all__ = ["ReaderTreeConfig", "ReaderTreeReport", "run_reader_tree"]


@dataclasses.dataclass(frozen=True)
class ReaderTreeConfig:
    """Shape and physics of one reader-tree run.  ``hop_dt_s`` is the
    mean per-hop push latency (jittered ±50% per edge, seeded);
    ``kill`` schedules ``(t, tier, index)`` relay deaths; children
    re-parent to the dead relay's parent after ``reparent_dt_s``."""

    readers: int = 2048
    degree: int = 8
    depth: int = 2
    rounds: int = 150
    publish_dt_s: float = 0.01
    hop_dt_s: float = 0.002
    reparent_dt_s: float = 0.05
    seed: int = 0
    kill: Tuple[Tuple[float, int, int], ...] = ()

    def __post_init__(self):
        if self.readers < 1 or self.degree < 2 or self.depth < 0:
            raise ValueError("need readers >= 1, degree >= 2, depth >= 0")
        if self.rounds < 1 or self.publish_dt_s <= 0 or self.hop_dt_s < 0:
            raise ValueError("need rounds >= 1 and positive cadences")
        if self.readers > self.degree ** (self.depth + 1):
            # the honesty guard: a tree that cannot absorb the demand
            # at the declared degree must be rejected, not quietly
            # simulated with over-degree leaf fan-out — the live
            # fan-out limit would refuse those readers with ERR_BUSY
            raise ValueError(
                f"{self.readers} readers exceed tree capacity "
                f"{self.degree ** (self.depth + 1)} (= degree^(depth+1)"
                f" = {self.degree}^{self.depth + 1}); raise degree or "
                "depth")


@dataclasses.dataclass
class ReaderTreeReport:
    """What the acceptance predicates gate: per-tier worst staleness
    (in rounds, against the publisher's live round at delivery time),
    zero torn (a torn push is modeled as not-delivered — the wire
    contract — so any cursor regression or duplicate would surface in
    those counters instead), zero duplicates, zero regressions, and
    coverage (every reader kept receiving after the kills)."""

    readers: int = 0
    relays: int = 0
    deliveries: int = 0
    duplicates: int = 0
    regressions: int = 0
    torn: int = 0
    skipped_total: int = 0
    worst_staleness_by_tier: Dict[int, int] = dataclasses.field(
        default_factory=dict)
    min_reader_final_round: int = -1
    max_reader_final_round: int = -1
    readers_served: int = 0

    def as_dict(self) -> Dict:
        return {
            "readers": self.readers, "relays": self.relays,
            "deliveries": self.deliveries,
            "duplicates": self.duplicates,
            "regressions": self.regressions, "torn": self.torn,
            "skipped_total": self.skipped_total,
            "worst_staleness_by_tier": {
                str(k): v for k, v in
                sorted(self.worst_staleness_by_tier.items())},
            "min_reader_final_round": self.min_reader_final_round,
            "max_reader_final_round": self.max_reader_final_round,
            "readers_served": self.readers_served,
        }


class _Node:
    """One tree participant: a relay tier node or a leaf reader."""

    __slots__ = ("name", "tier", "parent", "children", "cursor", "alive",
                 "pending", "busy", "received", "dup", "reg", "skipped")

    def __init__(self, name: str, tier: int):
        self.name = name
        self.tier = tier
        self.parent: Optional["_Node"] = None
        self.children: List["_Node"] = []
        self.cursor = -1
        self.alive = True
        # per-child pending round (skip-to-latest: one in-flight push
        # per edge; a newer round overwrites the pending one)
        self.pending: Dict[str, int] = {}
        self.busy: Dict[str, bool] = {}
        self.received = 0
        self.dup = 0
        self.reg = 0
        self.skipped = 0


def run_reader_tree(cfg: ReaderTreeConfig) -> ReaderTreeReport:
    """Run one deterministic reader-tree scenario; see module doc."""
    loop = EventLoop()
    root = _Node("root", 0)
    relays: List[_Node] = []
    tiers: List[List[_Node]] = [[root]]
    # tier widths, computed leaf-up so EVERY tier's fan-out respects
    # the configured degree: the leaf tier is just wide enough for the
    # readers at <= degree each, and each tier above is just wide
    # enough for the tier below at <= degree each (the capacity guard
    # in the config guarantees the recursion bottoms out <= degree at
    # tier 1)
    widths: List[int] = []
    need = max(1, -(-cfg.readers // cfg.degree))
    for _t in range(cfg.depth, 0, -1):
        widths.append(need)
        need = max(1, -(-need // cfg.degree))
    widths.reverse()
    for t in range(1, cfg.depth + 1):
        tier_nodes = []
        for i in range(widths[t - 1]):
            node = _Node(f"t{t}r{i}", t)
            parent = tiers[t - 1][i % len(tiers[t - 1])]
            node.parent = parent
            parent.children.append(node)
            tier_nodes.append(node)
            relays.append(node)
        tiers.append(tier_nodes)
    leaf_tier = tiers[-1]
    readers: List[_Node] = []
    for i in range(cfg.readers):
        node = _Node(f"reader{i}", cfg.depth + 1)
        parent = leaf_tier[i % len(leaf_tier)]
        node.parent = parent
        parent.children.append(node)
        readers.append(node)

    pub_round = [-1]
    worst_stale: Dict[int, int] = {}

    lat_memo: Dict[Tuple[str, str], float] = {}

    def edge_latency(parent: _Node, child: _Node) -> float:
        # one seeded draw per EDGE, memoized: the jitter is structural
        # (keyed by the edge's names), so re-deriving the RNG on every
        # push would recompute the same constant in the hot path
        key = (parent.name, child.name)
        lat = lat_memo.get(key)
        if lat is None:
            rng = rng_for(cfg.seed, "edge", parent.name, child.name)
            lat = cfg.hop_dt_s * (0.5 + rng.random())
            lat_memo[key] = lat
        return lat

    def push(parent: _Node, child: _Node) -> None:
        """Schedule delivery of the parent's pending round to one
        child; at-most-one in flight per edge (skip-to-latest)."""
        if parent.busy.get(child.name) or child.name not in parent.pending:
            return
        parent.busy[child.name] = True
        loop.after(edge_latency(parent, child),
                   lambda: deliver(parent, child))

    def deliver(parent: _Node, child: _Node) -> None:
        parent.busy[child.name] = False
        rnd = parent.pending.pop(child.name, None)
        if rnd is None or not parent.alive:
            return  # a dead parent's in-flight push is a torn frame:
            # modeled as NOT delivered — the child's cursor is untouched
        if not child.alive or child.parent is not parent:
            return  # the child re-parented mid-flight; stale edge
        if rnd == child.cursor:
            child.dup += 1
        elif rnd < child.cursor:
            child.reg += 1
        else:
            if child.cursor >= 0:
                child.skipped += max(0, rnd - child.cursor - 1)
            child.cursor = rnd
            child.received += 1
            stale = max(0, pub_round[0] - rnd)
            if stale > worst_stale.get(child.tier, -1):
                worst_stale[child.tier] = stale
            land(child, rnd)
        if child.name in parent.pending:
            push(parent, child)

    def land(node: _Node, rnd: int) -> None:
        """Forward a landed round to every child edge."""
        for child in node.children:
            node.pending[child.name] = rnd
            push(node, child)

    def publish() -> None:
        if pub_round[0] + 1 >= cfg.rounds:
            return
        pub_round[0] += 1
        land(root, pub_round[0])
        root.cursor = pub_round[0]
        loop.after(cfg.publish_dt_s, publish)

    def kill(tier: int, index: int) -> None:
        victims = [n for n in relays if n.tier == tier]
        if not victims or index >= len(victims):
            return
        node = victims[index]
        node.alive = False
        node.pending.clear()
        grand = node.parent
        for child in list(node.children):
            # the re-parent: the child re-attaches to its grandparent
            # after the reconnect delay, CURSOR PRESERVED — the resumed
            # stream promises strictly above it, exactly the live
            # Subscriber.reparent contract
            def reattach(child=child, grand=grand):
                if not child.alive:
                    return
                child.parent = grand
                grand.children.append(child)
                if grand.cursor > child.cursor:
                    grand.pending[child.name] = grand.cursor
                    push(grand, child)
            loop.after(cfg.reparent_dt_s, reattach)
        node.children = []

    loop.at(0.0, publish)
    for (t, tier, index) in cfg.kill:
        loop.at(float(t), (lambda a, b: lambda: kill(a, b))(
            int(tier), int(index)))
    horizon = cfg.rounds * cfg.publish_dt_s \
        + (cfg.depth + 2) * (cfg.hop_dt_s * 2 + cfg.reparent_dt_s) + 1.0
    loop.run(until=horizon,
             max_events=40 * cfg.rounds * (cfg.readers + len(relays) + 8))

    rep = ReaderTreeReport(readers=len(readers), relays=len(relays))
    for node in readers + relays:
        rep.deliveries += node.received
        rep.duplicates += node.dup
        rep.regressions += node.reg
        rep.skipped_total += node.skipped
    rep.worst_staleness_by_tier = dict(worst_stale)
    finals = [r.cursor for r in readers]
    rep.min_reader_final_round = min(finals)
    rep.max_reader_final_round = max(finals)
    rep.readers_served = sum(1 for f in finals if f >= 0)
    return rep
