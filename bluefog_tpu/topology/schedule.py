"""Compile a virtual topology into an XLA-ready gossip schedule.

This is the TPU-native replacement for the reference's
``MPI_Dist_graph_create_adjacent`` step (``bluefog/common/mpi_context.cc``,
upstream-relative): where the reference pushes the virtual graph into the MPI
library and lets ``MPI_Neighbor_allgatherv`` route payloads, we decompose the
digraph into a minimal sequence of *partial permutations* (matchings), each of
which lowers to exactly one ``lax.ppermute`` over the ICI mesh.

Two decompositions:

1. **Circulant fast path** — every standard Bluefog topology (ring, exp2,
   symmetric-exp, fully-connected, one-peer dynamic phases) is circulant: its
   edge set is a union of complete shift classes ``{i -> i+s (mod n)}``.  Each
   shift class is already a full permutation, which XLA lowers to a single
   rotation riding the ICI torus — optimal.
2. **Greedy edge coloring** — arbitrary digraphs (star, grid, user graphs) are
   colored so no two edges in a slot share a source or a destination; König's
   theorem bounds the optimum by max(in_degree, out_degree) and greedy stays
   close in practice.

The per-slot receive weights live in small ``(n, K)`` arrays indexed by
``lax.axis_index`` inside the jitted step, so *weights* can vary per rank and
per call without recompilation — only the edge structure is compile-time.
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence, Tuple

import numpy as np

from bluefog_tpu.topology.graphs import Topology

__all__ = ["GossipSchedule", "build_schedule"]

Perm = Tuple[Tuple[int, int], ...]


@dataclasses.dataclass(frozen=True, eq=False)
class GossipSchedule:
    """A topology lowered to ppermute slots + per-rank weight tables.

    ``eq=False``: identity equality/hash — schedules ride through jit as
    static metadata (e.g. in ``WindowSpec``), so reuse the same instance
    across steps to keep the compilation cache warm.

    Attributes:
      size: number of ranks.
      perms: one partial permutation per slot; each is a tuple of ``(src, dst)``
        pairs with all sources distinct and all destinations distinct.
      self_weights: ``(n,)`` — diagonal of the mixing matrix.
      recv_weights: ``(n, K)`` — weight rank ``i`` applies to the payload
        arriving in slot ``k`` (0 where no edge).
      recv_src: ``(n, K)`` int — source rank feeding rank ``i``'s slot ``k``,
        or -1 (used for neighbor_allgather ordering and masking).
      is_circulant: True when every slot is a complete shift permutation.
    """

    size: int
    perms: Tuple[Perm, ...]
    self_weights: np.ndarray
    recv_weights: np.ndarray
    recv_src: np.ndarray
    is_circulant: bool
    name: str = "schedule"

    @property
    def num_slots(self) -> int:
        return len(self.perms)

    def validate(self) -> None:
        for k, perm in enumerate(self.perms):
            srcs = [s for s, _ in perm]
            dsts = [d for _, d in perm]
            if len(set(srcs)) != len(srcs) or len(set(dsts)) != len(dsts):
                raise ValueError(f"slot {k} is not a partial permutation: {perm}")

    def mixing_matrix(self) -> np.ndarray:
        """Reconstruct the dense row-stochastic matrix (for tests)."""
        w = np.diag(self.self_weights.copy())
        for k, perm in enumerate(self.perms):
            for (src, dst) in perm:
                w[dst, src] += self.recv_weights[dst, k]
        return w


def _try_circulant_slots(topo: Topology) -> List[Perm] | None:
    """If the edge set is a union of complete shift classes, return one full
    rotation permutation per shift; else None."""
    n = topo.size
    edges = set(topo.edges)
    shifts = sorted({(dst - src) % n for (src, dst) in edges})
    for s in shifts:
        if any(((i, (i + s) % n)) not in edges for i in range(n)):
            return None
    if len(shifts) * n != len(edges):
        return None
    return [tuple((i, (i + s) % n) for i in range(n)) for s in shifts]


def _greedy_color_slots(topo: Topology) -> List[Perm]:
    """Greedy proper edge coloring of the digraph into partial permutations."""
    slots: List[List[Tuple[int, int]]] = []
    slot_srcs: List[set] = []
    slot_dsts: List[set] = []
    # Sort for determinism; high-degree endpoints first reduces color count.
    deg = lambda e: topo.out_degree(e[0]) + topo.in_degree(e[1])
    for (src, dst) in sorted(topo.edges, key=lambda e: (-deg(e), e)):
        placed = False
        for k in range(len(slots)):
            if src not in slot_srcs[k] and dst not in slot_dsts[k]:
                slots[k].append((src, dst))
                slot_srcs[k].add(src)
                slot_dsts[k].add(dst)
                placed = True
                break
        if not placed:
            slots.append([(src, dst)])
            slot_srcs.append({src})
            slot_dsts.append({dst})
    return [tuple(sorted(s)) for s in slots]


def build_schedule(topo: Topology, name: str | None = None) -> GossipSchedule:
    """Lower a :class:`Topology` to a :class:`GossipSchedule`."""
    n = topo.size
    circ = _try_circulant_slots(topo)
    perms = circ if circ is not None else _greedy_color_slots(topo)
    k_slots = len(perms)
    recv_w = np.zeros((n, max(k_slots, 1)))
    recv_src = np.full((n, max(k_slots, 1)), -1, dtype=np.int32)
    for k, perm in enumerate(perms):
        for (src, dst) in perm:
            recv_w[dst, k] = topo.weights[dst, src]
            recv_src[dst, k] = src
    sched = GossipSchedule(
        size=n,
        perms=tuple(perms),
        self_weights=np.array([topo.self_weight(r) for r in range(n)]),
        recv_weights=recv_w,
        recv_src=recv_src,
        is_circulant=circ is not None,
        name=name or topo.name,
    )
    sched.validate()
    if not np.allclose(sched.mixing_matrix(), topo.weights, atol=1e-9):
        raise AssertionError("schedule does not reproduce the mixing matrix")
    return sched
