"""Virtual topology library — graphs, weights, dynamic generators, schedules.

TPU-native re-implementation of the reference topology layer
(``bluefog/common/topology_util.py``, upstream-relative — see SURVEY.md §2.2).
The reference returns ``networkx.DiGraph`` objects; here the core object is a
:class:`Topology` wrapping a dense row-stochastic weight matrix, which is what
the XLA lowering actually needs.  ``networkx`` interop is provided when the
library is installed.
"""

from bluefog_tpu.topology.graphs import (
    Topology,
    ExponentialTwoGraph,
    ExponentialGraph,
    SymmetricExponentialGraph,
    RingGraph,
    MeshGrid2DGraph,
    StarGraph,
    FullyConnectedGraph,
    IsTopologyEquivalent,
    IsRegularGraph,
    GetRecvWeights,
    GetSendWeights,
    heal,
    replan,
    replan_penalized,
)
from bluefog_tpu.topology.dynamic import (
    GetDynamicOnePeerSendRecvRanks,
    GetExp2DynamicSendRecvMachineRanks,
    GetInnerOuterRingDynamicSendRecvRanks,
    GetInnerOuterExpo2DynamicSendRecvRanks,
    one_peer_exponential_two_schedules,
    one_peer_ring_schedules,
    one_peer_exp2_mixing_matrix,
    dynamic_topologies_from_generator,
)
from bluefog_tpu.topology.schedule import GossipSchedule, build_schedule
from bluefog_tpu.topology.mapping import ici_ring_order, remap_topology
