"""Virtual-graph -> physical ICI mesh embedding.

The reference hands its virtual graph to MPI and lets the fabric route
(``MPI_Dist_graph_create_adjacent`` in ``bluefog/common/mpi_context.cc``,
upstream-relative).  On TPU the physical network is an ICI torus with known
device coordinates, so we can do better: order the devices so that the hot
virtual edges are physical ICI hops.

- Ring topologies embed exactly: a snake (boustrophedon) walk over the torus
  coordinates makes every ``i -> i+1`` edge a single ICI hop.
- Exponential-2 edges become power-of-two strides along the snake, which XLA's
  collective-permute handles with torus wraparound links.

On hosts without coordinates (CPU test meshes) the identity order is used.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from bluefog_tpu.topology.graphs import Topology

__all__ = ["ici_ring_order", "remap_topology"]


def ici_ring_order(devices: Optional[Sequence] = None) -> List:
    """Order devices along a snaking path over their (x, y, z) torus coords so
    consecutive devices are ICI-adjacent.  Falls back to ``id`` order when
    coords are unavailable (CPU/virtual devices)."""
    import jax

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    coords = []
    for d in devices:
        c = getattr(d, "coords", None)
        if c is None:
            return sorted(devices, key=lambda d: d.id)
        coords.append(tuple(c))
    dims = len(coords[0])

    def snake_key(c):
        # Boustrophedon: reverse the traversal direction of each inner axis
        # depending on the parity of the outer axes, so each step moves one hop.
        key = []
        flip = 0
        for i in range(dims):
            v = c[i] if flip % 2 == 0 else -c[i]
            key.append(v)
            flip += c[i] if i < dims - 1 else 0
        return tuple(key)

    order = sorted(range(len(devices)), key=lambda i: snake_key(coords[i]))
    return [devices[i] for i in order]


def remap_topology(topo: Topology, perm: Sequence[int]) -> Topology:
    """Relabel ranks: new rank ``i`` plays old rank ``perm[i]``'s role.

    ``W'[i, j] = W[perm[i], perm[j]]``.  Used to align a virtual topology with
    a physical device ordering chosen by :func:`ici_ring_order`."""
    p = np.asarray(perm)
    if sorted(p.tolist()) != list(range(topo.size)):
        raise ValueError("perm must be a permutation of range(size)")
    w = topo.weights[np.ix_(p, p)]
    return Topology(weights=w, name=f"{topo.name}|remap")
