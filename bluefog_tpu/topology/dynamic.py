"""Dynamic (time-varying) topology generators.

Parity target: the dynamic-topology helpers of the reference's
``bluefog/common/topology_util.py`` (upstream-relative): per-rank infinite
generators (``GetDynamicOnePeerSendRecvRanks`` and the machine-aware
inner-outer variants) that the reference feeds into per-call
``src_weights``/``dst_weights`` of ``neighbor_allreduce``.

TPU twist: per-call arbitrary weights would retrigger XLA compilation, so the
JAX-native path materializes one *period* of the dynamic process as a list of
:class:`~bluefog_tpu.topology.graphs.Topology` objects (all these generators
are periodic) and compiles one ``lax.switch`` over per-phase gossip schedules
— see ``bluefog_tpu.ops.collectives.neighbor_allreduce_dynamic`` and
SURVEY.md §7 "Hard parts #2".
"""

from __future__ import annotations

import math
from typing import Callable, Generator, Iterator, List, Optional, Tuple

import numpy as np

from bluefog_tpu.topology.graphs import Topology

__all__ = [
    "GetDynamicOnePeerSendRecvRanks",
    "GetExp2DynamicSendRecvMachineRanks",
    "GetInnerOuterRingDynamicSendRecvRanks",
    "GetInnerOuterExpo2DynamicSendRecvRanks",
    "one_peer_exponential_two_schedules",
    "one_peer_ring_schedules",
    "one_peer_exp2_mixing_matrix",
    "dynamic_topologies_from_generator",
]

SendRecv = Tuple[List[int], List[int]]


def GetDynamicOnePeerSendRecvRanks(
    topo: Topology, self_rank: int
) -> Generator[SendRecv, None, None]:
    """Cycle through the static topology's neighbors one peer at a time.

    Yields ``(send_ranks, recv_ranks)`` — one out-neighbor and one in-neighbor
    per step, in sorted-offset order, repeating forever.  Mirrors the upstream
    generator of the same name used for dynamic exponential-2 training
    (BASELINE.json config[1] flavor).
    """
    out_nbrs = sorted(topo.out_neighbors(self_rank), key=lambda d: (d - self_rank) % topo.size)
    in_nbrs = sorted(topo.in_neighbors(self_rank), key=lambda s: (self_rank - s) % topo.size)
    if not out_nbrs or not in_nbrs:
        while True:
            yield ([], [])
    i = 0
    while True:
        yield ([out_nbrs[i % len(out_nbrs)]], [in_nbrs[i % len(in_nbrs)]])
        i += 1


def GetExp2DynamicSendRecvMachineRanks(
    world_size: int, local_size: int, self_rank: int, local_rank: int
) -> Generator[SendRecv, None, None]:
    """Machine-level one-peer exponential-2 generator (upstream name).

    For hierarchical dynamic training: only the designated cross-machine rank
    (``local_rank == 0`` by convention) participates; yields the *global* rank
    of the paired machine's cross-rank.
    """
    if world_size % local_size != 0:
        raise ValueError("world_size must be divisible by local_size")
    n_machines = world_size // local_size
    machine = self_rank // local_size
    phases = max(1, math.ceil(math.log2(n_machines))) if n_machines > 1 else 0
    if phases == 0 or local_rank != 0:
        while True:
            yield ([], [])
    k = 0
    while True:
        o = 2 ** (k % phases)
        send_m = (machine + o) % n_machines
        recv_m = (machine - o) % n_machines
        yield ([send_m * local_size + local_rank], [recv_m * local_size + local_rank])
        k += 1


def _inner_outer(
    world_size: int,
    local_size: int,
    self_rank: int,
    outer_offsets: List[int],
) -> Generator[SendRecv, None, None]:
    """Alternate an intra-machine ring step with a cross-machine step.

    Even phases: unidirectional ring inside the machine.  Odd phases: the
    rank communicates with the same local_rank on another machine, cycling
    through ``outer_offsets`` (machine-index offsets).
    """
    n_machines = world_size // local_size
    machine, local = divmod(self_rank, local_size)
    k = 0
    outer_i = 0
    while True:
        if k % 2 == 0 and local_size > 1:
            send = machine * local_size + (local + 1) % local_size
            recv = machine * local_size + (local - 1) % local_size
            yield ([send], [recv])
        elif n_machines > 1 and outer_offsets:
            o = outer_offsets[outer_i % len(outer_offsets)]
            send = ((machine + o) % n_machines) * local_size + local
            recv = ((machine - o) % n_machines) * local_size + local
            outer_i += 1
            yield ([send], [recv])
        else:
            yield ([], [])
        k += 1


def GetInnerOuterRingDynamicSendRecvRanks(
    world_size: int, local_size: int, self_rank: int
) -> Generator[SendRecv, None, None]:
    """Upstream-named inner(machine-ring)/outer(cross-machine-ring) generator."""
    if world_size % local_size != 0:
        raise ValueError("world_size must be divisible by local_size")
    return _inner_outer(world_size, local_size, self_rank, outer_offsets=[1])


def GetInnerOuterExpo2DynamicSendRecvRanks(
    world_size: int, local_size: int, self_rank: int
) -> Generator[SendRecv, None, None]:
    """Upstream-named inner-ring / outer-exponential-2 generator."""
    if world_size % local_size != 0:
        raise ValueError("world_size must be divisible by local_size")
    n_machines = world_size // local_size
    offs, o = [], 1
    while o < n_machines:
        offs.append(o)
        o *= 2
    return _inner_outer(world_size, local_size, self_rank, outer_offsets=offs)


# ---------------------------------------------------------------------------
# JAX-native periodic schedules
# ---------------------------------------------------------------------------


def _one_peer_shift_topology(size: int, shift: int) -> Topology:
    """Everyone sends to ``rank + shift``: a full permutation matching with
    1/2–1/2 mixing weights (the one-peer gossip matrix)."""
    w = np.zeros((size, size))
    for r in range(size):
        src = (r - shift) % size
        if src == r:
            w[r, r] = 1.0
        else:
            w[r, r] = 0.5
            w[r, src] = 0.5
    return Topology(weights=w, name=f"OnePeerShift({shift})")


def one_peer_exponential_two_schedules(size: int) -> List[Topology]:
    """One period of the one-peer dynamic exponential-2 process:
    phase ``k`` pairs ``i -> i + 2^k (mod n)`` with 1/2–1/2 weights.

    This is the time-varying graph sequence of the reference's dynamic-exp2
    training mode, materialized for ``lax.switch`` compilation.
    """
    if size <= 1:
        return [_one_peer_shift_topology(size, 0)]
    phases = math.ceil(math.log2(size))
    return [_one_peer_shift_topology(size, 2**k) for k in range(phases)]


def one_peer_ring_schedules(size: int) -> List[Topology]:
    """Two-phase one-peer ring: alternate sending right / left."""
    if size <= 1:
        return [_one_peer_shift_topology(size, 0)]
    if size == 2:
        return [_one_peer_shift_topology(size, 1)]
    return [_one_peer_shift_topology(size, 1), _one_peer_shift_topology(size, -1)]


def one_peer_exp2_mixing_matrix(size: int, step):
    """Jittable ``step -> (n, n)`` mixing matrix for one-peer dynamic exp2.

    ``step`` may be a **traced** integer (e.g. the optimizer's communication
    counter): phase ``step % ceil(log2 n)`` pairs ``i -> i + 2^phase (mod n)``
    with 1/2–1/2 weights — the same process as
    :func:`one_peer_exponential_two_schedules`, but produced as *data* for
    :func:`~bluefog_tpu.ops.collectives.neighbor_allreduce_aperiodic`
    (arbitrary per-step edge sets, zero recompilation) instead of a
    pre-compiled ``lax.switch`` period.
    """
    import jax.numpy as jnp

    if size <= 1:
        return jnp.ones((1, 1), jnp.float32)
    phases = math.ceil(math.log2(size))
    # 2^(phase) < size always: phase <= ceil(log2 n) - 1 => shift <= 2^(ceil-1) < n
    shift = jnp.left_shift(1, jnp.asarray(step, jnp.int32) % phases)
    rows = jnp.arange(size)
    srcs = (rows - shift) % size  # src != row since 0 < shift < size
    w = jnp.zeros((size, size), jnp.float32)
    return w.at[rows, rows].set(0.5).at[rows, srcs].set(0.5)


def dynamic_topologies_from_generator(
    size: int,
    gen_factory: Callable[[int], Iterator[SendRecv]],
    num_steps: int,
    name: str = "dynamic",
) -> List[Topology]:
    """Materialize ``num_steps`` global topologies from per-rank generators.

    ``gen_factory(rank)`` must return the rank's ``(send, recv)`` generator
    (e.g. ``lambda r: GetDynamicOnePeerSendRecvRanks(topo, r)``).  Each step's
    edge set is the union of every rank's send list that step; weights are
    uniform ``1/(in_degree+1)``.  Consistency between send and recv lists is
    validated — mismatches would deadlock the reference's MPI path and produce
    wrong averages here.
    """
    gens = [gen_factory(r) for r in range(size)]
    topos: List[Topology] = []
    for step in range(num_steps):
        edges = []
        recv_claims = set()
        for r in range(size):
            send, recv = next(gens[r])
            for d in send:
                edges.append((r, d))
            for s in recv:
                recv_claims.add((s, r))
        if set(edges) != recv_claims:
            raise ValueError(
                f"step {step}: send/recv lists inconsistent: "
                f"sends {sorted(set(edges) - recv_claims)} unclaimed, "
                f"recvs {sorted(recv_claims - set(edges))} unmatched"
            )
        topos.append(Topology.from_edges(size, edges, name=f"{name}[{step}]"))
    return topos
