"""Static virtual topologies with row-stochastic mixing weights.

Parity target: the graph constructors of the reference's
``bluefog/common/topology_util.py`` (upstream-relative; mount was empty during
the survey, see SURVEY.md header).  Constructor names (`ExponentialTwoGraph`,
`ExponentialGraph`, `RingGraph`, `MeshGrid2DGraph`, ...) are confirmed by
BASELINE.json; weight conventions follow the Bluefog paper (arXiv:2111.04287):
each row of the mixing matrix sums to 1, with uniform ``1/(in_degree+1)``
weights for the exponential/ring/star families and Metropolis–Hastings weights
for the 2-D grid (symmetric doubly-stochastic).

Orientation convention
----------------------
``W[i, j]`` is the weight rank ``i`` applies to the tensor *received from*
rank ``j``; edge ``j -> i`` exists iff ``W[i, j] > 0`` (for ``i != j``).
``W[i, i]`` is the self weight.  One gossip step computes

    out_i = W[i, i] * x_i  +  sum_{j in InNbr(i)} W[i, j] * x_j

which matches the reference's ``neighbor_allreduce(tensor, self_weight,
src_weights)`` semantics.
"""

from __future__ import annotations

import dataclasses
import math
import re
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "Topology",
    "ExponentialTwoGraph",
    "ExponentialGraph",
    "SymmetricExponentialGraph",
    "RingGraph",
    "MeshGrid2DGraph",
    "StarGraph",
    "FullyConnectedGraph",
    "IsTopologyEquivalent",
    "IsRegularGraph",
    "GetRecvWeights",
    "GetSendWeights",
    "heal",
    "replan",
    "replan_penalized",
]


@dataclasses.dataclass(frozen=True, eq=False)
class Topology:
    """A directed, weighted virtual communication graph.

    ``eq=False``: identity-based equality/hash so instances can serve as
    static (hashable) metadata under jit; semantic comparison goes through
    :func:`IsTopologyEquivalent`.

    Attributes:
      weights: ``(n, n)`` float64 row-stochastic matrix, orientation per the
        module docstring.
      name: human-readable tag used in logs / timeline spans.
      inactive: ranks that are currently NOT participating (healed-out
        corpses, drained leavers, not-yet-joined slots): their rows are
        inert identity self-loops and no active row references them.
        Rank indices stay valid across membership change — the
        join/rejoin path needs stable numbering — and :func:`heal` /
        :func:`replan` use this set to keep the derived ``name`` a
        single collapsed suffix instead of an ever-growing chain.
    """

    weights: np.ndarray
    name: str = "custom"
    inactive: FrozenSet[int] = frozenset()

    def __post_init__(self):
        object.__setattr__(self, "inactive",
                           frozenset(int(r) for r in self.inactive))
        w = np.asarray(self.weights, dtype=np.float64)
        if w.ndim != 2 or w.shape[0] != w.shape[1]:
            raise ValueError(f"weights must be square, got shape {w.shape}")
        if (w < -1e-12).any():
            raise ValueError("weights must be non-negative")
        rows = w.sum(axis=1)
        if not np.allclose(rows, 1.0, atol=1e-8):
            raise ValueError(f"weights must be row-stochastic; row sums {rows}")
        object.__setattr__(self, "weights", w)

    # -- basic queries -------------------------------------------------------

    @property
    def size(self) -> int:
        return self.weights.shape[0]

    def self_weight(self, rank: int) -> float:
        return float(self.weights[rank, rank])

    def in_neighbors(self, rank: int) -> List[int]:
        """Ranks whose tensors ``rank`` receives (sorted)."""
        row = self.weights[rank]
        return [j for j in range(self.size) if j != rank and row[j] > 0.0]

    def out_neighbors(self, rank: int) -> List[int]:
        """Ranks to which ``rank`` sends (sorted)."""
        col = self.weights[:, rank]
        return [i for i in range(self.size) if i != rank and col[i] > 0.0]

    def in_degree(self, rank: int) -> int:
        return len(self.in_neighbors(rank))

    def out_degree(self, rank: int) -> int:
        return len(self.out_neighbors(rank))

    @property
    def max_in_degree(self) -> int:
        return max(self.in_degree(r) for r in range(self.size))

    @property
    def edges(self) -> List[Tuple[int, int]]:
        """Directed edge list as ``(src, dst)`` pairs (dst receives from src)."""
        n = self.size
        return [
            (j, i)
            for i in range(n)
            for j in range(n)
            if i != j and self.weights[i, j] > 0.0
        ]

    # -- conversions ---------------------------------------------------------

    def to_networkx(self):
        """Return the reference-style ``networkx.DiGraph`` with edge weights.

        Edge ``(u, v)`` carries ``weight=W[v, u]`` (v receives from u), and each
        node carries a self-loop with the self weight, mirroring the upstream
        convention of self-loops in the topology digraph.
        """
        import networkx as nx  # optional dependency

        g = nx.DiGraph()
        g.add_nodes_from(range(self.size))
        for i in range(self.size):
            g.add_edge(i, i, weight=self.weights[i, i])
            for j in self.in_neighbors(i):
                g.add_edge(j, i, weight=self.weights[i, j])
        return g

    @staticmethod
    def from_networkx(graph, name: str = "networkx") -> "Topology":
        """Build from a reference-style weighted DiGraph (self-loops = self weight)."""
        n = graph.number_of_nodes()
        w = np.zeros((n, n))
        for (u, v, data) in graph.edges(data=True):
            w[v, u] = data.get("weight", 0.0)
        # Unweighted digraph: assign uniform 1/(in_degree+1) rows.
        if w.sum() == 0.0:
            for v in range(n):
                preds = [u for u in graph.predecessors(v) if u != v]
                k = len(preds) + 1
                w[v, v] = 1.0 / k
                for u in preds:
                    w[v, u] = 1.0 / k
        return Topology(weights=w, name=name)

    @staticmethod
    def from_edges(
        size: int,
        edges: Sequence[Tuple[int, int]],
        weights: Optional[Dict[Tuple[int, int], float]] = None,
        name: str = "custom",
    ) -> "Topology":
        """Build from a ``(src, dst)`` edge list.

        Without explicit ``weights``, each row gets uniform ``1/(in_degree+1)``
        (the reference's un-weighted ``set_topology(topo, is_weighted=False)``
        behavior).
        """
        w = np.zeros((size, size))
        if weights is None:
            indeg = [0] * size
            for (_, dst) in edges:
                indeg[dst] += 1
            for i in range(size):
                w[i, i] = 1.0 / (indeg[i] + 1)
            for (src, dst) in edges:
                w[dst, src] = 1.0 / (indeg[dst] + 1)
        else:
            for (src, dst) in edges:
                w[dst, src] = weights[(src, dst)]
            for i in range(size):
                w[i, i] = 1.0 - w[i].sum()
        return Topology(weights=w, name=name)


# ---------------------------------------------------------------------------
# Constructors
# ---------------------------------------------------------------------------


def _uniform_from_out_offsets(size: int, offsets_fn, name: str) -> Topology:
    """Build a circulant-style digraph: rank ``i`` sends to ``i + o (mod n)``.

    Weights: uniform ``1/(in_degree + 1)`` per receiving rank (the reference's
    default for the exponential family).
    """
    w = np.zeros((size, size))
    indeg = np.zeros(size, dtype=int)
    edge = np.zeros((size, size), dtype=bool)
    for i in range(size):
        for o in offsets_fn(i):
            dst = (i + o) % size
            if dst != i and not edge[dst, i]:
                edge[dst, i] = True
                indeg[dst] += 1
    for i in range(size):
        w[i, i] = 1.0 / (indeg[i] + 1)
        for j in range(size):
            if edge[i, j]:
                w[i, j] = 1.0 / (indeg[i] + 1)
    return Topology(weights=w, name=name)


def ExponentialGraph(size: int, base: int = 2) -> Topology:
    """Static exponential graph: ``i -> (i + base**k) % size`` for all
    ``base**k < size``.

    Reference: ``topology_util.ExponentialGraph`` (upstream-relative; name
    confirmed in BASELINE.json).
    """
    if size < 1:
        raise ValueError("size must be >= 1")
    offsets = []
    o = 1
    while o < size:
        offsets.append(o)
        o *= base
    return _uniform_from_out_offsets(size, lambda i: offsets, f"ExponentialGraph(base={base})")


def ExponentialTwoGraph(size: int) -> Topology:
    """Exponential-2 graph — the reference's default topology and the core of
    its decentralized-SGD recipe (``topology_util.ExponentialTwoGraph``,
    confirmed in BASELINE.json)."""
    t = ExponentialGraph(size, base=2)
    return dataclasses.replace(t, name="ExponentialTwoGraph")


def SymmetricExponentialGraph(size: int, base: int = 4) -> Topology:
    """Bidirectional exponential graph: edges to ``i ± base**k``
    (``topology_util.SymmetricExponentialGraph``, upstream)."""
    offsets = []
    o = 1
    while o < size:
        offsets.append(o)
        offsets.append(-o)
        o *= base
    return _uniform_from_out_offsets(
        size, lambda i: offsets, f"SymmetricExponentialGraph(base={base})"
    )


def RingGraph(size: int, connect_style: int = 0) -> Topology:
    """Ring topology (``topology_util.RingGraph``, confirmed in BASELINE.json).

    connect_style: 0 = bidirectional (neighbors at ±1), 1 = unidirectional
    right (``i -> i+1``), 2 = unidirectional left — matching the upstream
    tri-state argument.
    """
    if connect_style not in (0, 1, 2):
        raise ValueError("connect_style must be 0, 1 or 2")
    if connect_style == 0:
        offs = [1, -1]
    elif connect_style == 1:
        offs = [1]
    else:
        offs = [-1]
    t = _uniform_from_out_offsets(size, lambda i: offs, f"RingGraph(style={connect_style})")
    return t


def MeshGrid2DGraph(size: int, shape: Optional[Tuple[int, int]] = None) -> Topology:
    """2-D (non-wraparound) mesh grid with Metropolis–Hastings weights.

    Reference: ``topology_util.MeshGrid2DGraph`` (name confirmed in
    BASELINE.json).  Ranks are laid out row-major on an ``nrows x ncols`` grid
    (the most-square factorization of ``size`` when ``shape`` is omitted) with
    edges to the 4-neighborhood.  Weights are Metropolis–Hastings
    ``W[i,j] = 1 / (max(deg_i, deg_j) + 1)`` with the remainder on the
    diagonal — symmetric and doubly stochastic, the standard choice for grid
    gossip (used by the gradient-tracking / EXTRA configs in BASELINE.json).
    """
    if shape is None:
        a = int(math.floor(math.sqrt(size)))
        while size % a != 0:
            a -= 1
        shape = (a, size // a)
    nrows, ncols = shape
    if nrows * ncols != size:
        raise ValueError(f"shape {shape} does not match size {size}")

    def nbrs(r: int) -> List[int]:
        y, x = divmod(r, ncols)
        out = []
        for dy, dx in ((1, 0), (-1, 0), (0, 1), (0, -1)):
            yy, xx = y + dy, x + dx
            if 0 <= yy < nrows and 0 <= xx < ncols:
                out.append(yy * ncols + xx)
        return out

    deg = [len(nbrs(r)) for r in range(size)]
    w = np.zeros((size, size))
    for i in range(size):
        for j in nbrs(i):
            w[i, j] = 1.0 / (max(deg[i], deg[j]) + 1.0)
        w[i, i] = 1.0 - w[i].sum()
    return Topology(weights=w, name=f"MeshGrid2DGraph{shape}")


def StarGraph(size: int, center_rank: int = 0) -> Topology:
    """Star topology: bidirectional edges between ``center_rank`` and every
    other rank, uniform ``1/(in_degree+1)`` weights
    (``topology_util.StarGraph``, upstream)."""
    edges = []
    for r in range(size):
        if r != center_rank:
            edges.append((center_rank, r))
            edges.append((r, center_rank))
    t = Topology.from_edges(size, edges, name=f"StarGraph(center={center_rank})")
    return t


def FullyConnectedGraph(size: int) -> Topology:
    """Complete digraph with uniform ``1/size`` weights — one gossip step is an
    exact average (``topology_util.FullyConnectedGraph``, upstream)."""
    w = np.full((size, size), 1.0 / size)
    return Topology(weights=w, name="FullyConnectedGraph")


# ---------------------------------------------------------------------------
# Queries matching the reference API
# ---------------------------------------------------------------------------


def IsRegularGraph(topo: Topology) -> bool:
    """True iff every rank's in-degree equals its out-degree (upstream
    ``topology_util.IsRegularGraph``)."""
    return all(topo.in_degree(r) == topo.out_degree(r) for r in range(topo.size))


def IsTopologyEquivalent(a: Optional[Topology], b: Optional[Topology]) -> bool:
    """Structural + weight equivalence (upstream
    ``topology_util.IsTopologyEquivalent``)."""
    if a is None or b is None:
        return False
    if a.size != b.size:
        return False
    return bool(np.allclose(a.weights, b.weights, atol=1e-9))


def GetRecvWeights(topo: Topology, rank: int) -> Tuple[float, Dict[int, float]]:
    """``(self_weight, {src_rank: weight})`` for the receiving side of one
    gossip step (upstream ``topology_util.GetRecvWeights``)."""
    return topo.self_weight(rank), {j: float(topo.weights[rank, j]) for j in topo.in_neighbors(rank)}


def GetSendWeights(topo: Topology, rank: int) -> Tuple[float, Dict[int, float]]:
    """``(self_weight, {dst_rank: weight})`` — the weight each destination will
    apply to this rank's tensor (upstream ``topology_util.GetSendWeights``)."""
    return topo.self_weight(rank), {i: float(topo.weights[i, rank]) for i in topo.out_neighbors(rank)}


# a healed/replanned name carries exactly ONE provenance suffix; repeated
# membership change collapses it instead of accreting "+heal(...)+heal(...)"
# into every metric label and blackbox event of a long churn run
_PROVENANCE_RE = re.compile(r"(\+(heal|replan|ctl)\([^)]*\))+$")


def _base_name(name: str) -> str:
    """Strip any existing ``+heal(...)``/``+replan(...)`` suffix chain."""
    return _PROVENANCE_RE.sub("", name)


def heal(topo: Topology, dead_ranks) -> Topology:
    """Re-normalize the mixing weights over the ranks that survive
    ``dead_ranks`` — the self-healing step the fault-tolerant gossip
    loops take when a peer is declared DEAD.

    Each surviving row drops its dead in-neighbors' columns and is
    rescaled by the surviving row mass (weights keep their *relative*
    proportions), so it stays row-stochastic; a survivor whose every
    neighbor died degenerates to a pure self-loop.  Dead rows are
    replaced by identity self-loops — their indices stay valid (rank
    numbering is stable across the failure, which the rejoin path
    needs), but no surviving row references them.

    Push-sum unbiasedness through the change: the (x, p) weight channel
    de-biases whatever row-stochastic matrix is in effect per round, so
    switching to the healed matrix mid-run keeps the surviving average
    unbiased — mass simply stops flowing toward the corpse.  A REJOINED
    rank is re-admitted by healing with it removed from ``dead_ranks``
    (typically ``heal(topo, dead - {rejoined})`` at a round boundary).

    ``heal(topo, [])`` returns ``topo`` unchanged; killing every rank is
    a ``ValueError`` (there is no one left to average).

    Composition: ``heal(heal(t, a), b)`` equals ``heal(t, a | b)`` — the
    renormalization preserves relative proportions, so healing is
    order-free over the union of dead sets — and the derived ``name``
    carries ONE collapsed ``+heal([union])`` suffix (never a chain), with
    the union tracked on :attr:`Topology.inactive`."""
    dead = frozenset(int(r) for r in dead_ranks)
    if not dead:
        return topo
    n = topo.size
    bad = [r for r in dead if not (0 <= r < n)]
    if bad:
        raise ValueError(f"dead ranks {sorted(bad)} out of range for "
                         f"size-{n} topology")
    all_dead = dead | topo.inactive
    if len(all_dead) >= n:
        raise ValueError("cannot heal a topology with every rank dead")
    w = topo.weights.copy()
    for r in dead:
        w[r, :] = 0.0
        w[:, r] = 0.0
        w[r, r] = 1.0
    for i in range(n):
        if i in all_dead:
            continue
        s = w[i].sum()
        if s <= 0.0:
            w[i, i] = 1.0  # every neighbor died: isolated self-loop
        else:
            w[i] /= s
    return Topology(weights=w,
                    name=f"{_base_name(topo.name)}+heal({sorted(all_dead)})",
                    inactive=all_dead)


# the replan constructor ladder: the best graph family per live-member
# count m, balancing spectral gap against degree caps as the fleet grows
# and shrinks — a tiny fleet affords the one-step exact averager, a large
# one caps out-degree at ~log2(m) with the exponential family
_REPLAN_FULL_MAX = 4


def _replan_graph(m: int) -> Topology:
    if m == 1:
        return Topology(weights=np.ones((1, 1)), name="self")
    if m <= _REPLAN_FULL_MAX:
        return FullyConnectedGraph(m)
    return ExponentialGraph(m, base=2)


def replan(topo: Topology, members, *, name: Optional[str] = None
           ) -> Topology:
    """Build a FRESH mixing plan over the *current* member set — the
    generalization of :func:`heal` for intentional membership change
    (ranks joining and leaving a running job, not just dying).

    Where ``heal`` renormalizes the existing edge structure over the
    survivors (inert self-loop padding for the dead — right for an
    unplanned death mid-round), ``replan`` re-optimizes: it constructs a
    new graph over the ``m = len(members)`` live ranks (one-step exact
    averaging for tiny fleets, the exponential-2 family — out-degree
    ``~log2(m)``, strong connectivity, healthy spectral gap — beyond),
    then embeds it into the full ``n x n`` index space so rank numbering
    stays stable: non-members become inert identity self-loops, exactly
    the shape the rejoin/admission path expects.

    Determinism is the coordination-free contract: the plan depends ONLY
    on ``(topo.size, sorted(members))``, so every rank computing
    ``replan`` from the same member list converges on the SAME matrix
    with no extra rendezvous.  ``replan(replan(t, m1), m2) ==
    replan(t, m2)`` — replanning is memoryless over member sets.

    ``members`` must be a non-empty subset of ``range(topo.size)``.  The
    result's :attr:`Topology.inactive` is the complement and the name is
    a single collapsed ``+replan(n=m)`` suffix."""
    n = topo.size
    mem = sorted({int(r) for r in members})
    if not mem:
        raise ValueError("cannot replan over an empty member set")
    bad = [r for r in mem if not (0 <= r < n)]
    if bad:
        raise ValueError(f"member ranks {bad} out of range for "
                         f"size-{n} topology")
    m = len(mem)
    small = _replan_graph(m)
    w = np.zeros((n, n))
    idx = np.array(mem)
    w[np.ix_(idx, idx)] = small.weights
    mem_set = frozenset(mem)
    for r in range(n):
        if r not in mem_set:
            w[r, r] = 1.0
    return Topology(
        weights=w,
        name=name or f"{_base_name(topo.name)}+replan(n={m})",
        inactive=frozenset(range(n)) - frozenset(mem))


# the densify ladder the communication controller climbs when measured
# mixing lags the spectral-gap prediction: each level trades more edges
# (wire volume, ack pressure) for a larger spectral gap.  Level 0 is the
# replan base family (out-degree ~log2 m), level 1 doubles the edge set
# with the symmetric exponential family, level 2 is the one-step exact
# averager.
MAX_DENSIFY = 2


def _densify_graph(m: int, level: int) -> Topology:
    if m == 1:
        return Topology(weights=np.ones((1, 1)), name="self")
    if level >= 2 or m <= _REPLAN_FULL_MAX:
        return FullyConnectedGraph(m)
    if level == 1:
        return SymmetricExponentialGraph(m, base=2)
    return _replan_graph(m)


def replan_penalized(topo: Topology, members, *, slow=(),
                     densify: int = 0, name: Optional[str] = None
                     ) -> Topology:
    """The communication controller's actuation form of :func:`replan`:
    a fresh mixing plan over ``members`` with **per-peer penalties**
    applied — a peer in ``slow`` (a slow rank, a lossy link) keeps only
    its canonical RING edges over the sorted member list, so its degree
    drops from the family's ~log2(m) to exactly one in-edge and one
    out-edge.  Strong connectivity is preserved by construction (the
    ring spine covers every member), so the penalized graph still
    passes the B-connectivity verifier — consensus keeps flowing, just
    not at the worst link's pace.  ``densify`` raises the base family's
    edge budget (0 = replan base, 1 = symmetric exponential, 2 = fully
    connected) when measured mixing lags the spectral-gap prediction.

    Determinism is the coordination-free contract, exactly as for
    :func:`replan`: the result depends ONLY on ``(topo.size,
    sorted(members), sorted(slow & members), min(densify, MAX_DENSIFY))``
    — every rank deciding from the same disseminated evidence converges
    on the SAME matrix with no rendezvous.  Memoryless over member sets
    and penalty sets; slow ranks outside ``members`` are ignored.  With
    no penalties and ``densify=0`` this is exactly ``replan``.

    The derived ``name`` carries one collapsed ``+ctl(...)`` suffix
    (the heal/replan provenance convention)."""
    n = topo.size
    mem = sorted({int(r) for r in members})
    if not mem:
        raise ValueError("cannot replan over an empty member set")
    bad = [r for r in mem if not (0 <= r < n)]
    if bad:
        raise ValueError(f"member ranks {bad} out of range for "
                         f"size-{n} topology")
    level = max(0, min(int(densify), MAX_DENSIFY))
    pen = sorted({int(r) for r in slow} & set(mem))
    if not pen and level == 0:
        base = replan(topo, mem)
        return base if name is None else dataclasses.replace(
            base, name=name)
    m = len(mem)
    small = _densify_graph(m, level)
    w_small = small.weights.copy()
    if pen and m > 1:
        # drop every edge incident to a penalized member EXCEPT the
        # canonical ring spine i -> (i+1) mod m over the sorted member
        # list — degree reduction that can never disconnect the graph
        pen_idx = {mem.index(r) for r in pen}
        edge = w_small > 0.0
        np.fill_diagonal(edge, False)
        for i in range(m):
            for j in range(m):
                if not edge[i, j]:
                    continue
                if i in pen_idx or j in pen_idx:
                    if i != (j + 1) % m:  # keep ring edge j -> j+1
                        edge[i, j] = False
        # re-uniform rows over the surviving edges (1/(in_degree+1))
        w_small = np.zeros((m, m))
        for i in range(m):
            nbrs = [j for j in range(m) if edge[i, j]]
            k = len(nbrs) + 1
            w_small[i, i] = 1.0 / k
            for j in nbrs:
                w_small[i, j] = 1.0 / k
    w = np.zeros((n, n))
    idx = np.array(mem)
    w[np.ix_(idx, idx)] = w_small
    mem_set = frozenset(mem)
    for r in range(n):
        if r not in mem_set:
            w[r, r] = 1.0
    return Topology(
        weights=w,
        name=name or (f"{_base_name(topo.name)}"
                      f"+ctl(n={m},slow={pen},densify={level})"),
        inactive=frozenset(range(n)) - mem_set)
